"""SLO-driven serving control loop tests (reference scope: serve
autoscaling_policy tests + the PR-11 tentpole's serving control loop).

Covers: windowed attainment math, the router's bounded full-jitter retry
backoff with attempt-tagged latency observations, the degradation ladder
(engine admission tightening + shed-to-cheaper-model routing), graceful
scale-down draining in-flight requests, and the diurnal-load soak whose
recovery is asserted against the cluster event journal.
"""

import threading
import time

import pytest

import ray_tpu as rt
from ray_tpu import serve
from ray_tpu.serve.controller import (ServeController, _DeploymentState,
                                      windowed_attainment)
from ray_tpu.serve.router import (RETRY_BASE_S, RETRY_CAP_S,
                                  RETRY_MAX_ATTEMPTS, DeploymentResponse,
                                  Router, backoff_delay)


# ----------------------------------------------------------- unit: window


def test_windowed_attainment():
    now = 1000.0

    def rec(done=True, finished_at=999.0, ttft=0.01, tpot=0.001,
            dur=0.5):
        return {"done": done, "t0_wall": finished_at - dur, "e2e": dur,
                "ttft": ttft, "tpot": tpot}

    # all inside the window and under target
    a, n = windowed_attainment([rec(), rec()], now, 10.0, 0.2, 0.02)
    assert (a, n) == (1.0, 2)
    # ttft breach and tpot breach each fail the request
    a, n = windowed_attainment(
        [rec(), rec(ttft=5.0), rec(tpot=5.0)], now, 10.0, 0.2, 0.02)
    assert n == 3 and a == pytest.approx(1 / 3)
    # finished outside the window / still in flight: not counted
    a, n = windowed_attainment(
        [rec(finished_at=900.0, ttft=5.0), rec(done=False, ttft=5.0)],
        now, 10.0, 0.2, 0.02)
    assert (a, n) == (1.0, 0)
    # a 1-token request has no TPOT: only TTFT judges it
    a, n = windowed_attainment([rec(tpot=None)], now, 10.0, 0.2, 0.02)
    assert (a, n) == (1.0, 1)


# ---------------------------------------------------- unit: router backoff


def test_backoff_delay_full_jitter_bounds():
    for attempt in range(12):
        for _ in range(50):
            d = backoff_delay(attempt)
            assert 0.0 <= d <= min(RETRY_CAP_S,
                                   RETRY_BASE_S * 2 ** attempt)
    # the cap bounds even absurd attempt counts (no float overflow blowup)
    assert backoff_delay(500) <= RETRY_CAP_S


def test_result_retries_bounded_with_attempt_tags(monkeypatch):
    """Replica-death retries are bounded by RETRY_MAX_ATTEMPTS, back off
    between rounds, and tag every latency observation with the attempt
    number — the old behavior was unbounded fixed-interval hammering."""
    from ray_tpu.exceptions import ActorError

    calls = {"get": 0, "retry": 0}

    def dead_get(ref, timeout=None):
        calls["get"] += 1
        raise ActorError("replica died")

    monkeypatch.setattr(rt, "get", dead_get)
    notes = []
    resp = DeploymentResponse(
        object(), retry=lambda: (calls.__setitem__(
            "retry", calls["retry"] + 1), object())[1],
        note=lambda outcome, attempt=0: notes.append((outcome, attempt)))
    t0 = time.monotonic()
    with pytest.raises(ActorError):
        resp.result(timeout=5)
    elapsed = time.monotonic() - t0
    assert calls["get"] == RETRY_MAX_ATTEMPTS
    assert calls["retry"] == RETRY_MAX_ATTEMPTS - 1
    # retry rounds observed with their attempt number; the terminal
    # failure observed as outcome="error"
    assert notes[:-1] == [("retry", i)
                          for i in range(1, RETRY_MAX_ATTEMPTS)]
    assert notes[-1] == ("error", RETRY_MAX_ATTEMPTS - 1)
    # it actually backed off (sum of three full-jitter draws is >0 with
    # overwhelming probability, and bounded by the un-jittered sum)
    assert elapsed <= sum(min(RETRY_CAP_S, RETRY_BASE_S * 2 ** a)
                          for a in range(RETRY_MAX_ATTEMPTS)) + 1.0


def test_router_apply_shed_counts(monkeypatch):
    from ray_tpu.util import metrics as metrics_mod

    router = Router.__new__(Router)
    router._name = "shedder"
    router._shed_to = ""
    assert router._apply_shed("") == ""
    assert router._apply_shed("big-model") == "big-model"
    router._shed_to = "tiny"
    before = sum(metrics_mod.snapshot().get(
        "serve_overload_shed_total", {}).get("values", {}).values())
    assert router._apply_shed("big-model") == "tiny"
    assert router._apply_shed("") == "tiny"
    # a caller already on the shed target is not re-shed (or re-counted)
    assert router._apply_shed("tiny") == "tiny"
    after = sum(metrics_mod.snapshot().get(
        "serve_overload_shed_total", {}).get("values", {}).values())
    assert after == before + 2


# ------------------------------------------------- unit: degradation ladder


def test_set_overload_level_scales_token_budget():
    from types import SimpleNamespace

    from ray_tpu.llm.serve_llm import LLMServer
    srv = SimpleNamespace(engine=SimpleNamespace(step_token_budget=2048))
    assert LLMServer.set_overload_level(srv, 1, 0.5) == 1024
    assert LLMServer.set_overload_level(srv, 2, 0.5) == 512
    assert LLMServer.set_overload_level(srv, 0) == 2048  # restore base
    # an unbounded base budget (0) still tightens, from the config default
    srv2 = SimpleNamespace(engine=SimpleNamespace(step_token_budget=0))
    assert 64 <= LLMServer.set_overload_level(srv2, 1, 0.5) < 2048
    assert LLMServer.set_overload_level(srv2, 0) == 0


class _FakeHead:
    def __init__(self):
        self.records = []

    def call(self, method, payload, timeout=None):
        assert method == "requests_dump"
        return list(self.records)


class _FakeReplica:
    def __init__(self):
        self.pushes = []
        outer = self

        class _M:
            def remote(self, method, args, kwargs):
                outer.pushes.append((method, args))

        self.handle_request = _M()


def _mk_controller(st):
    import collections
    ctrl = ServeController.__new__(ServeController)
    ctrl._lock = threading.RLock()
    ctrl._route_events = collections.deque()
    ctrl._route_kick = threading.Event()
    ctrl._deployments = {st.name: st}
    return ctrl


def _recs(ttft, n=5):
    now = time.time()
    return [{"done": True, "t0_wall": now - 0.2, "e2e": 0.1,
             "ttft": ttft, "tpot": 0.001} for _ in range(n)]


def test_slo_policy_ladder_and_shed_state_machine():
    """Drive _autoscale_slo through a full storm and recovery: scale out
    first, then climb the degradation ladder at max replicas, shed at the
    top, and unwind everything in reverse on sustained headroom."""
    st = _DeploymentState("llm", {"num_replicas": 1})
    replica = _FakeReplica()
    st.replicas = [replica]
    ctrl = _mk_controller(st)
    head = _FakeHead()
    journal = []
    ctrl._head_client = lambda: head
    ctrl._journal = lambda etype, **f: journal.append((etype, f))
    cfg = {"policy": "slo", "min_replicas": 1, "max_replicas": 2,
           "slo_eval_period_s": 0.0, "slo_window_s": 60.0,
           "target_attainment": 0.9, "overload_steps": 2,
           "overload_max_level": 2, "overload_budget_factor": 0.5,
           "scale_down_evals": 2, "shed_model_id": "cheap"}

    def step():
        ctrl._autoscale_slo(st, cfg)

    head.records = _recs(ttft=10.0)        # hard breach
    step()                                  # below max: scale out
    assert st.target_replicas == 2
    assert ("serve_autoscale" in [e for e, _ in journal])
    step()                                  # at max: streak 1, no action
    assert st.overload_level == 0
    step()                                  # streak 2 -> ladder level 1
    assert st.overload_level == 1
    step(); step()                          # streak 2 again -> level 2
    assert st.overload_level == 2
    v_before = st.version
    step(); step()                          # at top -> shed engages
    assert st.shed_to == "cheap" and st.version > v_before
    # replicas got the admission pushes (fire-and-forget dispatch)
    assert [a for m, a in replica.pushes
            if m == "set_overload_level"] == [(1, 0.5), (2, 0.5)]
    # the shed target reaches routers through the routing table
    assert ctrl.get_routing_table("llm")["shed_to"] == "cheap"

    head.records = _recs(ttft=0.001)       # recovered traffic
    step()                                  # unwind shed first
    assert st.shed_to == "" and st.overload_level == 2
    step(); step()                          # ladder 2 -> 1 -> 0
    assert st.overload_level == 0
    assert [a for m, a in replica.pushes
            if m == "set_overload_level"][-2:] == [(1, 0.5), (0, 0.5)]
    step(); step()                          # 2 ok evals -> drain one
    assert st.target_replicas == 1

    types = [e for e, _ in journal]
    for expected in ("serve_slo_breach", "serve_autoscale",
                     "serve_overload_level", "serve_overload_shed_on",
                     "serve_overload_shed_off", "serve_slo_recovered"):
        assert expected in types, (expected, types)
    # the storm replays in causal order from the journal alone
    assert types.index("serve_overload_shed_on") \
        < types.index("serve_overload_shed_off") \
        < types.index("serve_slo_recovered")
    downs = [f for e, f in journal if e == "serve_autoscale"
             and f.get("direction") == "down"]
    assert downs and downs[0]["reason"] == "slo_headroom"


# -------------------------------------------------------- cluster fixture


@pytest.fixture(scope="module")
def slo_rt():
    rt.init(num_cpus=6, _system_config={
        "object_store_memory_bytes": 128 * 1024 * 1024,
        "worker_pool_prestart": 2,
        "metrics_export_period_s": 0.25,
    })
    yield rt
    serve.shutdown()
    rt.shutdown()


# NOTE: deployment classes below define their record-synthesis helper as
# a method and import only inside method bodies — replica workers cannot
# resolve this test module's globals when unpickling the callable.


def _journal_events(etype="", deployment=""):
    from ray_tpu.core.worker import global_worker
    evs = global_worker.backend.head.call(
        "events_dump", {"type": etype} if etype else {}, timeout=10)
    if deployment:
        evs = [e for e in evs if e.get("deployment") == deployment]
    return evs


def test_scale_down_drain_completes_inflight(slo_rt):
    """Graceful scale-down: victims leave the routing table immediately
    but finish their in-flight requests before the replica is released."""
    @serve.deployment(name="drainer", num_replicas=2,
                      max_ongoing_requests=4)
    class Slow:
        def __call__(self, i):
            time.sleep(1.5)
            return i

    h = serve.run(Slow.bind())
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline \
            and serve.status()["drainer"]["ready_replicas"] < 2:
        time.sleep(0.2)
    assert serve.status()["drainer"]["ready_replicas"] == 2

    # load both replicas, then scale down mid-flight
    resps = [h.remote(i) for i in range(4)]
    time.sleep(0.3)  # let the requests land replica-side
    serve.run(Slow.options(num_replicas=1).bind())
    out = sorted(r.result(timeout=60) for r in resps)
    assert out == [0, 1, 2, 3], "drain dropped in-flight requests"
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        info = serve.status()["drainer"]
        if info["live_replicas"] == 1 and info["draining"] == 0:
            break
        time.sleep(0.2)
    info = serve.status()["drainer"]
    assert info["live_replicas"] == 1 and info["draining"] == 0, info
    serve.delete("drainer")


def test_overload_ladder_sheds_and_recovers(slo_rt):
    """End to end at max replicas: sustained SLO breach climbs the
    ladder (replicas receive set_overload_level pushes), sheds new
    requests to the cheaper multiplexed model, and unwinds once the
    breach clears — every step replayable from the event journal."""
    @serve.deployment(name="degrader", num_replicas=1,
                      max_ongoing_requests=8,
                      autoscaling_config={
                          "policy": "slo", "min_replicas": 1,
                          "max_replicas": 1, "slo_eval_period_s": 0.25,
                          "slo_window_s": 1.5, "target_attainment": 0.9,
                          "overload_steps": 2, "overload_max_level": 2,
                          "overload_budget_factor": 0.5,
                          "shed_model_id": "tiny-model"})
    class Degrader:
        def __init__(self):
            from ray_tpu.llm.request_log import FlightRecorder
            self.recorder = FlightRecorder(capacity=512,
                                           observe_metrics=False)
            self.levels = []

        def set_overload_level(self, level, budget_factor=0.5):
            self.levels.append((level, budget_factor))
            return level

        def seen_levels(self):
            return list(self.levels)

        def _record(self, ttft_s, tpot_s=0.002):
            import uuid as _uuid
            rec = self.recorder.start(_uuid.uuid4().hex, 8, 16)
            rec.note_admit(rec.t0, 0)
            rec.note_first(rec.t0 + ttft_s)
            rec.note_decode(rec.t0 + ttft_s + tpot_s, 1)
            rec.note_decode(rec.t0 + ttft_s + 2 * tpot_s, 1)
            self.recorder.finish(rec, rec.t0 + ttft_s + 3 * tpot_s,
                                 "stop")

        def __call__(self, ttft_s):
            from ray_tpu.serve import get_multiplexed_model_id
            self._record(ttft_s)
            return get_multiplexed_model_id()

    h = serve.run(Degrader.bind())
    h.remote(0.01).result(timeout=60)   # warm up

    # storm: every request records a hard TTFT breach; hold until the
    # ladder tops out and sheds
    deadline = time.monotonic() + 45
    shed_seen = ""
    while time.monotonic() < deadline:
        shed_seen = h.remote(0.7).result(timeout=30)
        info = serve.status()["degrader"]
        if info["shed_to"] == "tiny-model" and shed_seen == "tiny-model":
            break
        time.sleep(0.1)
    info = serve.status()["degrader"]
    assert info["shed_to"] == "tiny-model", info
    assert info["overload_level"] == 2, info
    assert shed_seen == "tiny-model", \
        "router never re-routed to the shed model"
    levels = h.seen_levels.remote().result(timeout=30)
    assert [lv for lv, _ in levels][:2] == [1, 2], levels
    # the handle's router counted its shed decisions
    from ray_tpu.util import metrics as metrics_mod
    shed_total = sum(metrics_mod.snapshot().get(
        "serve_overload_shed_total", {}).get("values", {}).values())
    assert shed_total >= 1

    # calm: breach records age out of the window -> full unwind
    deadline = time.monotonic() + 45
    while time.monotonic() < deadline:
        info = serve.status()["degrader"]
        if info["shed_to"] == "" and info["overload_level"] == 0:
            break
        time.sleep(0.25)
    info = serve.status()["degrader"]
    assert info["shed_to"] == "" and info["overload_level"] == 0, info

    types = [e["type"] for e in _journal_events()
             if e.get("deployment") == "degrader"]
    for expected in ("serve_slo_breach", "serve_overload_level",
                     "serve_overload_shed_on", "serve_overload_shed_off",
                     "serve_slo_recovered"):
        assert expected in types, (expected, types)
    assert types.index("serve_overload_shed_on") \
        < types.index("serve_overload_shed_off") \
        < types.index("serve_slo_recovered")
    serve.delete("degrader")


@pytest.mark.slow
def test_diurnal_load_slo_recovery_from_journal(slo_rt):
    """The diurnal soak: a synthetic load wave overloads the service,
    the SLO loop scales out until attainment recovers, and the calm
    phase packs back down — all asserted against the event journal."""
    OFFERED_STORM, OFFERED_CALM, CAP = 18, 2, 6

    @serve.deployment(name="diurnal", num_replicas=1,
                      max_ongoing_requests=32,
                      autoscaling_config={
                          "policy": "slo", "min_replicas": 1,
                          "max_replicas": 3, "slo_eval_period_s": 0.3,
                          "slo_window_s": 2.0, "target_attainment": 0.9,
                          "overload_steps": 10_000,
                          "scale_down_evals": 6})
    class Synthetic:
        def __init__(self):
            from ray_tpu.llm.request_log import FlightRecorder
            self.recorder = FlightRecorder(capacity=1024,
                                           observe_metrics=False)

        def _record(self, ttft_s, tpot_s=0.002):
            import uuid as _uuid
            rec = self.recorder.start(_uuid.uuid4().hex, 8, 16)
            rec.note_admit(rec.t0, 0)
            rec.note_first(rec.t0 + ttft_s)
            rec.note_decode(rec.t0 + ttft_s + tpot_s, 1)
            rec.note_decode(rec.t0 + ttft_s + 2 * tpot_s, 1)
            self.recorder.finish(rec, rec.t0 + ttft_s + 3 * tpot_s,
                                 "stop")

        def __call__(self, ttft_s):
            import time as _time
            _time.sleep(0.2)
            self._record(ttft_s)
            return ttft_s

    h = serve.run(Synthetic.bind())

    def round_trip(offered):
        # per-replica load decides latency: the diurnal model of a
        # fixed-capacity replica (CAP concurrent before TTFT collapses)
        n_live = max(1, serve.status()["diurnal"]["live_replicas"])
        ttft = 0.02 if offered / n_live <= CAP else 0.7
        resps = [h.remote(ttft) for _ in range(offered)]
        for r in resps:
            r.result(timeout=60)

    for _ in range(6):                      # morning calm
        round_trip(OFFERED_CALM)
    assert not _journal_events("serve_slo_breach", "diurnal"), \
        "calm traffic must not breach"

    storm_t0 = time.time()
    for _ in range(40):                     # midday storm
        round_trip(OFFERED_STORM)

    breaches = [e for e in _journal_events("serve_slo_breach",
                                           "diurnal")
                if e["ts"] >= storm_t0]
    assert breaches, "storm never registered as an SLO breach"
    ups = [e for e in _journal_events("serve_autoscale", "diurnal")
           if e.get("direction") == "up" and e["ts"] >= storm_t0]
    assert ups and ups[-1]["to"] == 3, \
        f"SLO loop never scaled to max: {ups}"
    assert all(e.get("reason") == "slo_attainment" for e in ups)
    # recovery: once capacity matched load, breaches STOPPED — within a
    # few controller evals of the last scale-up (window 2s + eval 0.3s)
    recover_by = ups[-1]["ts"] + 4.0
    late = [e for e in _journal_events("serve_slo_breach", "diurnal")
            if e["ts"] > recover_by]
    assert not late, \
        f"attainment never recovered after scale-up: {late[-3:]}"

    for _ in range(40):                     # evening calm: pack down
        round_trip(OFFERED_CALM)
        if serve.status()["diurnal"]["live_replicas"] == 1:
            break
    downs = [e for e in _journal_events("serve_autoscale", "diurnal")
             if e.get("direction") == "down"]
    assert downs and all(e["reason"] == "slo_headroom" for e in downs)
    assert serve.status()["diurnal"]["live_replicas"] == 1
    serve.delete("diurnal")
