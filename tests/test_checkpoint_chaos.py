"""Crash-consistent checkpoint protocol (ISSUE 14): commit-marker
semantics, async writer, GC of crash debris, prune ordering — plus the
chaos proof: SIGKILL a training worker mid-shard-write and mid-manifest
via fault_injector, restart, and assert restore lands on the previous
COMMITTED step with zero half-written dirs visible and the journal
chain (checkpoint_abandoned -> train_restore -> checkpoint_committed)
telling the whole story.

These run in the tier-1 CPU sweep (no TPU, no slow marker): the commit
protocol is pure storage-ordering logic and the kill targets are CPU
worker processes.
"""

import json
import os
import time

import numpy as np
import pytest

import ray_tpu as rt
from ray_tpu import train
from ray_tpu.parallel.mesh import MeshSpec
from ray_tpu.train.checkpoint import (Checkpoint, CheckpointManager,
                                      MANIFEST_FILE)


# ---------------------------------------------------------------- protocol
# unit-level: no cluster, no jax collectives


class TestCommitProtocol:
    def test_latest_skips_manifestless_dirs(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path))
        mgr.save({"x": np.array([1])}, 1)
        # a save that died mid-flight: shard present, no commit marker
        half = str(tmp_path / "checkpoint_00000002")
        os.makedirs(half)
        open(os.path.join(half, "shard-00000.npz"), "wb").write(b"partial")
        latest = CheckpointManager(str(tmp_path), rank=1).latest()
        assert latest is not None
        assert latest.path.endswith("checkpoint_00000001")

    def test_gc_debris_at_init(self, tmp_path):
        # the crash leftovers satellite: mkdtemp dirs, .removing.* aside
        # dirs, seam staging files, and manifestless checkpoint dirs all
        # get collected when a (rank-0) manager takes over the root
        os.makedirs(tmp_path / "tmpabc123")
        os.makedirs(tmp_path / ".removing.checkpoint_00000009.1234")
        open(tmp_path / "arrays.npz.tmp.999", "wb").close()
        half = tmp_path / "checkpoint_00000005"
        os.makedirs(half)
        open(half / "shard-00000.npz", "wb").close()
        mgr = CheckpointManager(str(tmp_path))
        mgr.save({"x": np.array([1])}, 7)
        left = sorted(os.listdir(tmp_path))
        assert left == ["checkpoint_00000007"], left

    def test_prune_only_removes_older_than_newest_commit(self, tmp_path):
        # the num_to_keep=1 + async race satellite: an in-flight
        # (manifestless) dir must never cause the only committed
        # checkpoint to be pruned
        mgr = CheckpointManager(str(tmp_path), num_to_keep=1)
        mgr.save({"x": np.array([1])}, 1)
        os.makedirs(tmp_path / "checkpoint_00000002")  # "in flight"
        mgr._prune()
        assert mgr.fs.exists(
            str(tmp_path / "checkpoint_00000001" / MANIFEST_FILE)), \
            "prune removed the only committed checkpoint"
        # once a NEWER manifest lands, the old one may go
        (tmp_path / "checkpoint_00000002").rmdir()
        mgr.save({"x": np.array([2])}, 2)
        mgr.flush()
        assert [d for d in sorted(os.listdir(tmp_path))
                if d.startswith("checkpoint_")] == ["checkpoint_00000002"]

    def test_resave_committed_step_drops_manifest_first(self, tmp_path,
                                                        fault_injector):
        mgr = CheckpointManager(str(tmp_path))
        mgr.save({"x": np.array([1])}, 1)
        # re-save same step, dying before the new shard lands: the OLD
        # manifest must already be gone (no stale-manifest/new-shard mix)
        fault_injector.configure("checkpoint.shard_write=raise")
        with pytest.raises(RuntimeError):
            mgr.save({"x": np.array([2])}, 1)
        assert CheckpointManager(str(tmp_path), rank=1).latest() is None

    def test_corrupt_falls_back_to_previous_committed(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path))
        mgr.save({"x": np.array([1])}, 1)
        mgr.save({"x": np.array([2])}, 2)
        newest = mgr.latest()
        with open(os.path.join(newest.path, "shard-00000.npz"), "wb") as f:
            f.write(b"bitrot")
        out = mgr.latest().load()
        assert int(out["x"][0]) == 1

    def test_corrupt_without_fallback_raises_typed(self, tmp_path):
        from ray_tpu.train import CheckpointCorrupt
        mgr = CheckpointManager(str(tmp_path))
        ck = mgr.save({"x": np.array([1])}, 1)
        with open(os.path.join(ck.path, "shard-00000.npz"), "wb") as f:
            f.write(b"bitrot")
        with pytest.raises(CheckpointCorrupt):
            Checkpoint(ck.path).load()


class TestAsyncWriter:
    def test_async_saves_commit_on_flush(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path), num_to_keep=2,
                                async_save=True)
        for step in (1, 2, 3):
            mgr.save_async({"x": np.array([step])}, step)
        mgr.flush()
        assert not mgr.in_flight()
        assert int(mgr.latest().load()["x"][0]) == 3
        dirs = [d for d in sorted(os.listdir(tmp_path))
                if d.startswith("checkpoint_")]
        assert dirs == ["checkpoint_00000002", "checkpoint_00000003"]

    def test_writer_error_surfaces_on_next_save(self, tmp_path,
                                                fault_injector):
        mgr = CheckpointManager(str(tmp_path), async_save=True)
        fault_injector.configure("checkpoint.shard_write=raise")
        mgr.save_async({"x": np.array([1])}, 1)
        mgr.flush(raise_errors=False)
        fault_injector.reset()
        with pytest.raises(RuntimeError, match="async checkpoint save"):
            mgr.save_async({"x": np.array([2])}, 2)
        # the error is consumed once surfaced; saves work again
        mgr.save_async({"x": np.array([3])}, 3)
        mgr.flush()
        assert int(mgr.latest().load()["x"][0]) == 3

    def test_writer_error_surfaces_at_flush(self, tmp_path, fault_injector):
        mgr = CheckpointManager(str(tmp_path), async_save=True)
        fault_injector.configure("checkpoint.manifest_write=raise")
        mgr.save_async({"x": np.array([1])}, 1)
        with pytest.raises(RuntimeError, match="async checkpoint save"):
            mgr.flush()
        assert mgr.latest() is None  # nothing committed


# ------------------------------------------------------------------ chaos

@pytest.fixture(scope="module")
def chaos_rt():
    rt.init(num_cpus=4, _system_config={
        "object_store_memory_bytes": 128 * 1024 * 1024,
    })
    from ray_tpu.core.worker import global_worker
    yield rt, global_worker.backend.head
    rt.shutdown()


def _make_kill_loop():
    """Numpy-params loop that arms a fault spec INSIDE the worker process
    right before the save at kill_step (guarded by a marker file so only
    the first incarnation arms it; fault_injector re-reads the env per
    fire, and SIGKILL leaves no process to leak the spec)."""
    def loop(cfg):
        from ray_tpu.util import fault_injector as fi
        ctx = train.get_context()
        params = np.zeros(4, np.float32)
        start = 0
        if ctx.get_checkpoint() is not None:
            state = ctx.get_checkpoint().load()
            params, start = state["params"], int(state["step"])
        for step in range(start, cfg["steps"]):
            params = params + 1.0
            if step == cfg["kill_step"] \
                    and not os.path.exists(cfg["armed_marker"]):
                open(cfg["armed_marker"], "w").close()
                os.environ[fi.ENV_VAR] = cfg["fault_spec"]
            train.report({"step": step},
                         checkpoint_tree={"params": params,
                                          "step": step + 1})
    return loop


def _run_kill_fit(chaos_rt, tmp_path, name, fault_spec):
    trainer = train.JaxTrainer(
        _make_kill_loop(),
        train_loop_config={"steps": 4, "kill_step": 1,
                           "armed_marker": str(tmp_path / "armed"),
                           "fault_spec": fault_spec},
        scaling_config=train.ScalingConfig(num_workers=1),
        run_config=train.RunConfig(
            name=name, storage_path=str(tmp_path),
            failure_config=train.FailureConfig(max_failures=1)))
    return trainer.fit()


def _events_for(head, run_dir):
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        evs = [e for e in head.call("events_dump", timeout=10)
               if run_dir in str(e.get("path", ""))]
        if any(e["type"] == "checkpoint_committed" for e in evs):
            return evs
        time.sleep(0.2)
    return []


def _assert_all_dirs_committed(run_dir):
    dirs = [d for d in sorted(os.listdir(run_dir))
            if d.startswith("checkpoint_")]
    assert dirs, "no checkpoints at all"
    for d in dirs:
        assert os.path.exists(os.path.join(run_dir, d, MANIFEST_FILE)), \
            f"half-written dir visible after recovery: {d}"
    return dirs


@pytest.mark.chaos
def test_sigkill_mid_manifest_restores_committed_step(chaos_rt, tmp_path):
    """The flagship round-trip: SIGKILL between the shard upload and the
    MANIFEST.json write. The dir has every byte of data but no commit
    marker — restart must GC it (checkpoint_abandoned), restore the
    PREVIOUS committed step (train_restore), and re-commit on the way to
    completion (checkpoint_committed), in that journal order."""
    rt_, head = chaos_rt
    result = _run_kill_fit(chaos_rt, tmp_path, "kill-manifest",
                           "checkpoint.manifest_write=kill9")
    assert result.error is None, result.error
    assert os.path.exists(tmp_path / "armed")  # the kill really happened
    run_dir = result.path

    # resumed from committed step 1 (the save at kill_step=1 never
    # committed; the dead incarnation's in-memory reports die with it):
    # the surviving history starts at _step == 2
    assert result.metrics_history[0]["_step"] == 2, result.metrics_history[0]
    assert result.metrics_history[0]["step"] == 1
    assert result.metrics_history[-1]["_step"] == 4
    # params prove continuity: 4 increments exactly, no lost or replayed
    # work beyond the uncommitted step
    assert float(result.checkpoint.load()["params"][0]) == 4.0

    dirs = _assert_all_dirs_committed(run_dir)
    assert dirs == [f"checkpoint_0000000{i}" for i in (1, 2, 3, 4)], dirs

    evs = _events_for(head, run_dir)
    ab = [e for e in evs if e["type"] == "checkpoint_abandoned"]
    tr = [e for e in evs if e["type"] == "train_restore"]
    cm = [e for e in evs if e["type"] == "checkpoint_committed"]
    assert ab and "checkpoint_00000002" in ab[0]["path"], evs
    assert tr and tr[0]["step"] == 1, evs
    recommits = [e for e in cm if e["seq"] > tr[0]["seq"]]
    assert [e["step"] for e in recommits] == [2, 3, 4], evs
    # causal chain: abandoned -> restore -> committed
    assert ab[0]["seq"] < tr[0]["seq"] < recommits[0]["seq"], evs
    # one trace id per save, all distinct and nonempty
    traces = [e["trace_id"] for e in cm]
    assert all(traces) and len(set(traces)) == len(traces), traces


@pytest.mark.chaos
def test_sigkill_mid_shard_write_restores_committed_step(chaos_rt,
                                                         tmp_path):
    """SIGKILL before the shard upload: the dying save leaves nothing at
    all (the shard put never ran), restart restores committed step 1 and
    training completes with every visible dir committed."""
    rt_, head = chaos_rt
    result = _run_kill_fit(chaos_rt, tmp_path, "kill-shard",
                           "checkpoint.shard_write=kill9")
    assert result.error is None, result.error
    assert os.path.exists(tmp_path / "armed")
    assert result.metrics_history[-1]["_step"] == 4
    assert float(result.checkpoint.load()["params"][0]) == 4.0
    _assert_all_dirs_committed(result.path)
    evs = _events_for(head, result.path)
    tr = [e for e in evs if e["type"] == "train_restore"]
    assert tr and tr[0]["step"] == 1, evs
    assert [e["step"] for e in evs
            if e["type"] == "checkpoint_committed"
            and e["seq"] > tr[0]["seq"]] == [2, 3, 4], evs


# ------------------------------------------------------- sharded multihost

def _make_sharded_loop():
    def loop(cfg):
        import jax
        import optax

        from ray_tpu.models import llama
        from ray_tpu.train.train_step import make_train_step, shard_params

        ctx = train.get_context()
        mesh = ctx.global_mesh()
        mcfg = llama.LlamaConfig.tiny(n_layers=2)
        params = llama.init_params(mcfg, jax.random.PRNGKey(11))
        with mesh:
            params = shard_params(params, mesh, llama.param_specs(mcfg))
            init_fn, _ = make_train_step(
                lambda p, b: llama.loss_fn(p, b, mcfg), optax.sgd(1e-2))
            init_fn(params)
            train.report({"ok": 1}, checkpoint_tree={"params": params})
    return loop


def test_multihost_save_is_sharded_no_full_tree_on_one_host(chaos_rt,
                                                            tmp_path):
    """Two processes save one FSDP-sharded tree: the manifest must show
    one shard per host, each well below the full-tree size — proof that
    no host ran a gather or serialized the whole model (the old
    process_allgather save path is really gone)."""
    result = train.JaxTrainer(
        _make_sharded_loop(),
        scaling_config=train.ScalingConfig(
            num_workers=2,
            mesh=MeshSpec(fsdp=-1),
            jax_distributed=True,
            jax_platform="cpu",
            local_device_count=4),
        run_config=train.RunConfig(
            name="sharded2", storage_path=str(tmp_path))).fit()
    assert result.error is None, result.error
    ck_dir = result.checkpoint.path
    manifest = json.load(open(os.path.join(ck_dir, MANIFEST_FILE)))
    shards = manifest["shards"]
    assert [s["name"] for s in shards] == ["shard-00000.npz",
                                           "shard-00001.npz"]
    total = sum(s["bytes"] for s in shards)
    for s in shards:
        assert 0 < s["bytes"] < 0.75 * total, (s, total)
    # and the sharded pieces reassemble into the full tree on load
    tree = Checkpoint(ck_dir).load()
    import jax
    n_params = sum(x.size for x in jax.tree.leaves(tree["params"]))
    assert n_params > 0
    assert all(np.isfinite(np.asarray(x)).all()
               for x in jax.tree.leaves(tree["params"]))
