"""JaxTrainer / checkpoint / controller tests (local mode + CPU mesh)."""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from ray_tpu.models.mlp import MLPConfig, mlp_init, mlp_loss
from ray_tpu.models import llama
from ray_tpu.parallel import MeshSpec, build_mesh
from ray_tpu.train import (CheckpointConfig, Checkpoint, FailureConfig,
                           JaxTrainer, RunConfig, ScalingConfig,
                           make_train_step, shard_params)
from ray_tpu.train.checkpoint import CheckpointManager
from ray_tpu.train.trainer import TrainingFailedError


class TestCheckpoint:
    def test_roundtrip_nested_pytree(self, tmp_path):
        tree = {"a": np.arange(6).reshape(2, 3),
                "b": [np.ones(4), {"c": np.float32(2.5)}],
                "d": (np.zeros(2), 7.0),
                "e": "hello"}
        ckpt = Checkpoint.save(tree, str(tmp_path / "ck"))
        back = ckpt.load()
        assert np.array_equal(back["a"], tree["a"])
        assert np.array_equal(back["b"][0], tree["b"][0])
        assert float(back["b"][1]["c"]) == 2.5
        assert isinstance(back["d"], tuple)
        assert back["e"] == "hello"

    def test_roundtrip_edge_pytrees(self, tmp_path):
        # keys with separators (haiku-style), empty containers, bare leaf
        tree = {"mlp/~/linear_0": {"w": np.ones(2)}, "empty": {},
                "elist": [], "etup": ()}
        back = Checkpoint.save(tree, str(tmp_path / "c1")).load()
        assert np.array_equal(back["mlp/~/linear_0"]["w"], np.ones(2))
        assert back["empty"] == {} and back["elist"] == [] \
            and back["etup"] == ()
        bare = Checkpoint.save(np.arange(3), str(tmp_path / "c2")).load()
        assert np.array_equal(bare, np.arange(3))

    def test_int_keys_roundtrip_in_numeric_order(self, tmp_path):
        # int keys >= 10 must restore as ints (numeric order), not strings
        # ('10' < '2' lexicographically would misassign leaves under
        # load(target=...)). Mixed int+str keys in one dict must survive too.
        tree = {"layers": {i: np.full(2, i, np.float32) for i in range(12)}}
        back = Checkpoint.save(tree, str(tmp_path / "ck")).load()
        assert set(back["layers"]) == set(tree["layers"])
        for k, v in tree["layers"].items():
            assert np.array_equal(back["layers"][k], v), k
        # target= zips leaves in jax.tree order; int keys sort numerically
        target = {"layers": {i: np.zeros(2, np.float32) for i in range(12)}}
        restored = Checkpoint(str(tmp_path / "ck")).load(target=target)
        for k, v in tree["layers"].items():
            assert np.array_equal(restored["layers"][k], v), k

    def test_load_into_target_structure(self, tmp_path):
        # namedtuple pytrees (optax states) normalize to tuples on save;
        # target= restores leaves into the live structure (orbax pattern).
        import optax
        cfg = MLPConfig(in_dim=8, hidden=8, out_dim=2)
        params = mlp_init(cfg, jax.random.PRNGKey(0))
        opt = optax.adam(1e-3)
        state = opt.init(params)
        ckpt = Checkpoint.save({"opt": state}, str(tmp_path / "ck"))
        template = {"opt": opt.init(params)}
        back = ckpt.load(target=template)["opt"]
        assert type(back) is type(state)
        chex = jax.tree.map(np.allclose, jax.tree.leaves(back),
                            jax.tree.leaves(state))
        assert all(jax.tree.leaves(chex))
        # the jitted step accepts the restored state
        _, g = jax.value_and_grad(mlp_loss)(
            params, (np.ones((4, 8), np.float32),
                     np.zeros((4,), np.int64)))
        opt.update(g, back, params)

    def test_manager_rotation(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path), num_to_keep=2)
        for step in range(5):
            mgr.save({"x": np.array([step])}, step)
        dirs = sorted(os.listdir(tmp_path))
        assert len(dirs) == 2
        assert mgr.latest().load()["x"][0] == 4

    def test_restore_onto_mesh(self, tmp_path):
        mesh = build_mesh(MeshSpec(fsdp=4, tp=2))
        tree = {"w": np.arange(32.0).reshape(8, 4)}
        ckpt = Checkpoint.save(tree, str(tmp_path / "ck"))
        shardings = {"w": NamedSharding(mesh, P("fsdp", "tp"))}
        back = ckpt.load(shardings=shardings)
        assert back["w"].sharding == shardings["w"]
        assert np.array_equal(np.asarray(back["w"]), tree["w"])


def _mlp_loop(config):
    from ray_tpu import train as rt_train
    ctx = rt_train.get_context()
    cfg = MLPConfig(in_dim=16, hidden=32, out_dim=4)
    params = mlp_init(cfg, jax.random.PRNGKey(0))
    start = 0
    if ctx.get_checkpoint() is not None:
        state = ctx.get_checkpoint().load()
        params, start = state["params"], int(state["step"])
    init_fn, step_fn = make_train_step(mlp_loss, optax.adam(1e-2))
    opt_state = init_fn(params)
    x = jax.random.normal(jax.random.PRNGKey(1), (64, 16))
    y = jax.random.randint(jax.random.PRNGKey(2), (64,), 0, 4)
    for step in range(start, config["steps"]):
        params, opt_state, metrics = step_fn(params, opt_state, (x, y))
        if config.get("fail_at") is not None and step == config["fail_at"] \
                and not os.path.exists(config["fail_marker"]):
            open(config["fail_marker"], "w").close()
            raise RuntimeError("injected worker failure")
        rt_train.report({"loss": float(metrics["loss"]), "step": step},
                        checkpoint_tree={"params": params, "step": step + 1})


class TestJaxTrainer:
    def test_mlp_end_to_end(self, rtpu_local, tmp_path):
        trainer = JaxTrainer(
            _mlp_loop,
            train_loop_config={"steps": 5, "fail_at": None},
            scaling_config=ScalingConfig(num_workers=1),
            run_config=RunConfig(name="mlp", storage_path=str(tmp_path)))
        result = trainer.fit()
        assert result.metrics["step"] == 4
        assert len(result.metrics_history) == 5
        losses = [m["loss"] for m in result.metrics_history]
        assert losses[-1] < losses[0]
        assert result.checkpoint is not None
        assert int(result.checkpoint.load()["step"]) == 5

    def test_failure_restart_resumes_from_checkpoint(self, rtpu_local,
                                                     tmp_path):
        marker = str(tmp_path / "failed_once")
        trainer = JaxTrainer(
            _mlp_loop,
            train_loop_config={"steps": 6, "fail_at": 3,
                               "fail_marker": marker},
            scaling_config=ScalingConfig(num_workers=1),
            run_config=RunConfig(
                name="mlp_ft", storage_path=str(tmp_path),
                failure_config=FailureConfig(max_failures=1)))
        result = trainer.fit()
        assert os.path.exists(marker)  # the failure really happened
        # resumed from step 3 (checkpoint written at step 2 → start=3)
        steps = [m["step"] for m in result.metrics_history]
        assert steps[-1] == 5
        assert result.checkpoint is not None
        # restart checkpoints continue the numbering — latest() is the
        # newest state, not a stale pre-failure dir
        from ray_tpu.train.checkpoint import CheckpointManager as CM
        assert CM.step_of(result.checkpoint.path) >= 6

    def test_checkpoint_frequency_thins_saves(self, rtpu_local, tmp_path):
        trainer = JaxTrainer(
            _mlp_loop,
            train_loop_config={"steps": 6, "fail_at": None},
            scaling_config=ScalingConfig(num_workers=1),
            run_config=RunConfig(
                name="freq", storage_path=str(tmp_path),
                checkpoint_config=CheckpointConfig(checkpoint_frequency=3)))
        result = trainer.fit()
        run_dir = result.path
        dirs = sorted(d for d in os.listdir(run_dir)
                      if d.startswith("checkpoint_"))
        # _mlp_loop offers a checkpoint every report; frequency=3 keeps
        # only steps 3 and 6
        assert dirs == ["checkpoint_00000003", "checkpoint_00000006"]

    def test_failure_budget_exhausted_raises(self, rtpu_local, tmp_path):
        def always_fail(config):
            raise RuntimeError("boom")

        trainer = JaxTrainer(
            always_fail,
            scaling_config=ScalingConfig(num_workers=1),
            run_config=RunConfig(name="f", storage_path=str(tmp_path),
                                 failure_config=FailureConfig(max_failures=1)))
        with pytest.raises(TrainingFailedError):
            trainer.fit()


class TestShardedTrainStep:
    def test_llama_fsdp_tp_step(self):
        mesh = build_mesh(MeshSpec(dp=2, fsdp=2, tp=2))
        cfg = llama.LlamaConfig.tiny(n_layers=2, dtype=jnp.float32)
        params = shard_params(llama.init_params(cfg, jax.random.PRNGKey(0)),
                              mesh, llama.param_specs(cfg))
        init_fn, step_fn = make_train_step(
            lambda p, b: llama.loss_fn(p, b, cfg), optax.adamw(1e-3))
        opt_state = init_fn(params)
        tokens = jax.device_put(
            jax.random.randint(jax.random.PRNGKey(1), (8, 32), 0,
                               cfg.vocab_size),
            NamedSharding(mesh, P(("dp", "fsdp"), None)))
        losses = []
        for _ in range(3):
            params, opt_state, m = step_fn(params, opt_state, tokens)
            losses.append(float(m["loss"]))
        assert losses[-1] < losses[0]
        assert all(np.isfinite(losses))
