"""cgroup-v2 worker isolation (SURVEY §2.1 cgroup row; reference:
src/ray/common/cgroup/cgroup_setup.h). The manager is exercised against a
fake unified hierarchy in a tmpdir — real kernels need delegation we can't
assume in CI — plus a no-op-degradation check against a non-cgroup dir."""

import os

from ray_tpu.runtime.cgroup import CgroupManager


def make_fake_root(tmp_path):
    root = tmp_path / "cg"
    root.mkdir()
    (root / "cgroup.controllers").write_text("cpuset cpu io memory pids\n")
    return str(root)


def test_slice_and_worker_leaf_lifecycle(tmp_path):
    root = make_fake_root(tmp_path)
    mgr = CgroupManager("sess1", root=root)
    assert mgr.enabled
    assert os.path.isdir(os.path.join(root, "rtpu-sess1"))
    # controllers requested for children
    sub = os.path.join(root, "rtpu-sess1", "cgroup.subtree_control")
    assert "+memory" in open(sub).read()

    leaf = mgr.create_worker_group("abcd" * 8,
                                   memory_bytes=256 * 1024 * 1024,
                                   num_cpus=2.0)
    assert leaf is not None and os.path.isdir(leaf)
    assert open(os.path.join(leaf, "memory.max")).read() == \
        str(256 * 1024 * 1024)
    assert open(os.path.join(leaf, "memory.oom.group")).read() == "1"
    assert open(os.path.join(leaf, "cpu.weight")).read() == "200"

    assert mgr.attach(leaf, 12345)
    assert open(os.path.join(leaf, "cgroup.procs")).read() == "12345"

    # kernel OOM-kill accounting parses
    with open(os.path.join(leaf, "memory.events"), "w") as f:
        f.write("low 0\nhigh 3\nmax 7\noom 1\noom_kill 1\n")
    ev = mgr.memory_events(leaf)
    assert ev["oom_kill"] == 1 and ev["max"] == 7

    # real cgroupfs rmdir succeeds while control files exist; the tmpfs
    # fake needs them cleared first to model that semantic
    for f in os.listdir(leaf):
        os.unlink(os.path.join(leaf, f))
    mgr.remove_worker_group(leaf)
    assert not os.path.isdir(leaf)
    os.unlink(sub)
    mgr.shutdown()
    assert not os.path.isdir(os.path.join(root, "rtpu-sess1"))


def test_degrades_to_noop_without_v2_root(tmp_path):
    mgr = CgroupManager("sess2", root=str(tmp_path / "not-cgroup"))
    assert not mgr.enabled
    assert mgr.create_worker_group("ffff" * 8, memory_bytes=1) is None
    assert not mgr.attach(None, 1)
    assert mgr.memory_events(None) == {}
    mgr.shutdown()  # no-op, no raise


def test_cpu_weight_bounds(tmp_path):
    root = make_fake_root(tmp_path)
    mgr = CgroupManager("sess3", root=root)
    tiny = mgr.create_worker_group("aa" * 16, num_cpus=0.001)
    assert open(os.path.join(tiny, "cpu.weight")).read() == "1"
    huge = mgr.create_worker_group("bb" * 16, num_cpus=500.0)
    assert open(os.path.join(huge, "cpu.weight")).read() == "10000"
    mgr.shutdown()


def test_node_spawn_passes_cpu_request_to_cgroup(tmp_path, monkeypatch):
    """The lease's CPU request reaches the worker leaf's cpu.weight via
    NodeDaemon._spawn_worker (num_cpus was dead code in
    create_worker_group until the node wired it through)."""
    import threading

    from ray_tpu.runtime import node as node_mod

    root = make_fake_root(tmp_path)
    mgr = CgroupManager("sess4", root=root)

    class FakeProc:
        pid = 4242
        returncode = None

        def wait(self):
            threading.Event().wait()  # parked: daemon thread, test-scoped

        def poll(self):
            return None

    monkeypatch.setattr(node_mod.subprocess, "Popen",
                        lambda *a, **k: FakeProc())
    nd = object.__new__(node_mod.NodeDaemon)
    nd.session = "sess4"
    nd.address = "127.0.0.1:0"
    nd.head_addr = "127.0.0.1:0"
    nd.shm_name = "shm"
    nd.cgroups = mgr
    nd.chips = None
    nd._lock = threading.Lock()
    nd._workers = {}
    entry = nd._spawn_worker(num_cpus=1.5)
    assert entry.cgroup_leaf is not None
    assert open(os.path.join(entry.cgroup_leaf,
                             "cpu.weight")).read() == "150"
    assert open(os.path.join(entry.cgroup_leaf,
                             "cgroup.procs")).read() == "4242"
