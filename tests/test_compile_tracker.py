"""XLA compile/dispatch observability plane (util/compile_tracker.py).

Units: jax-free import contract, shape/dtype signatures + recompile
diffs, the jit cache-miss wrap seam (probed and probeless paths, plus
in-flight attribution of anonymous jax.monitoring phase durations),
ring overflow with EXACT drop accounting (emitted == exported + stored
+ dropped across any export sequence), once-per-excursion compile-storm
journaling with re-arm, the head-side CompileStore (cursor, filters,
per-callable aggregation, LRU), and the multi-plane Perfetto export.

E2E: a two-node cluster where a shape-unstable jitted function run on
both nodes lands per-process compile records — recompiles carrying
their signature diff — at the head's CompileStore, increments
xla_recompiles_total, raises one compile_storm journal event per
process excursion, and exports a `trace --perfetto` file whose compile
+ span + train lanes share one clock.

Reference signal: TorchTitan and the Podracer report both treat silent
recompile storms as the dominant unexplained-latency failure on TPU
pods — this plane makes them cluster events instead.
"""

import json
import subprocess
import sys
import time

import numpy as np
import pytest

from ray_tpu.util import compile_tracker as ct

MiB = 1 << 20


# ----------------------------------------------------------------- lints

def test_compile_tracker_imports_without_jax():
    """Tier-1 contract: the tracker lives in the head and node daemons
    too (the head hosts the CompileStore), which must never pull in the
    accelerator stack. jax hookup is lazy and sys.modules-gated."""
    code = (
        "import sys; from ray_tpu.util import compile_tracker as ct; "
        "t = ct.CompileTracker(role='t'); "
        "t.note_compile('f', ['f32[8]']); "
        "e = t.export(); assert e and e['emitted'] == 1, e; "
        "s = ct.CompileStore(); s.ingest('w', e, role='worker'); "
        "assert s.dump()['records'], 'store empty'; "
        "tr = ct.ensure_started(role='t'); "
        "assert tr is not None and ct.drain_export() is None; "
        "print('jax' in sys.modules)")
    out = subprocess.run([sys.executable, "-c", code],
                         capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr
    assert out.stdout.strip() == "False", out.stdout


def test_ensure_started_respects_disable():
    from ray_tpu.core.config import GlobalConfig
    ct.stop_global()
    old = GlobalConfig.compile_tracker_enabled
    try:
        GlobalConfig.apply({"compile_tracker_enabled": False})
        assert ct.ensure_started(role="t") is None
        assert ct.get_global() is None
        assert ct.drain_export() is None
        assert ct.drain_journal_events() == []
    finally:
        GlobalConfig.apply({"compile_tracker_enabled": old})
        ct.stop_global()


# ----------------------------------------------------------------- units

def test_signature_of_jax_style():
    """Arrays render as the jit cache key's abstract part
    (dtype[shape]); scalars as weak type names; kwargs sorted; long
    arglists fold their tail so records stay bounded."""
    sig = ct.signature_of(
        (np.zeros((8, 16), np.float32), np.zeros((4,), np.int32),
         True, 3, 0.5, None, (np.zeros((2,), np.float16), 1)),
        {"b": np.zeros((1,), np.uint8), "a": 2})
    assert sig == ["f32[8,16]", "i32[4]", "bool", "int", "float",
                   "None", "(f16[2],int)", "a=int", "b=u8[1]"]
    folded = ct.signature_of([1] * 70)
    assert folded[-1] == "+6 more" and len(folded) == 65


def test_signature_diff_and_fingerprint():
    old = ["f32[8,16]", "i32[4]"]
    new = ["f32[9,16]", "i32[4]"]
    assert ct.signature_diff(old, new) == \
        ["arg[0]: f32[8,16] -> f32[9,16]"]
    assert ct.signature_diff(None, new) == []
    assert ct.signature_diff(["f32[8]"], ["f32[8]", "i32[4]"]) == \
        ["arity: 1 -> 2 args"]
    # diff list is capped
    d = ct.signature_diff([f"f32[{i}]" for i in range(20)],
                          [f"f32[{i + 1}]" for i in range(20)])
    assert d[-1] == "..." and len(d) == 9
    fp = ct.fingerprint("f", old)
    assert len(fp) == 12 and fp == ct.fingerprint("f", old)
    assert fp != ct.fingerprint("f", new)
    assert fp != ct.fingerprint("g", old)


def test_recompile_detection_synthetic_signatures():
    """Same callable + new signature == recompile, and the record
    carries the exact arg-level diff that caused it (the acceptance
    invariant for `compiles --recompiles`)."""
    tr = ct.CompileTracker(role="w", storm_threshold=0)
    r1 = tr.note_compile("model.step", ["f32[8,128]", "i32[8]"],
                         wall_s=1.0)
    assert not r1["recompile"] and r1["diff"] == [] and r1["nth"] == 1
    r2 = tr.note_compile("model.step", ["f32[9,128]", "i32[8]"],
                         wall_s=0.5)
    assert r2["recompile"] and r2["nth"] == 2
    assert r2["diff"] == ["arg[0]: f32[8,128] -> f32[9,128]"]
    assert r2["fingerprint"] != r1["fingerprint"]
    # identical signature again: cache hit territory, not a recompile
    r3 = tr.note_compile("model.step", ["f32[9,128]", "i32[8]"])
    assert not r3["recompile"] and r3["nth"] == 3
    # a different callable never cross-contaminates
    r4 = tr.note_compile("model.eval", ["f32[9,128]", "i32[8]"])
    assert not r4["recompile"]

    st = tr.callable_stats("model.step")
    assert st["compiles"] == 3 and st["recompiles"] == 1
    assert st["last_diff"] == r2["diff"]
    assert tr.callable_stats("missing") is None
    lr = tr.last_recompile()
    assert lr["name"] == "model.step" and lr["diff"] == r2["diff"]
    assert tr.last_recompile("model.") is not None
    assert tr.last_recompile("llm.") is None
    counts = tr.stats()["counts"]
    assert counts["jit"] == 4 and counts["recompile"] == 1


def test_ring_overflow_exact_drop_accounting():
    """The acceptance invariant: across any sequence of exports,
    emitted == exported + stored + dropped, to the record."""
    tr = ct.CompileTracker(ring_records=4, storm_threshold=0)
    for i in range(10):
        tr.note_compile("f", [f"f32[{i}]"])
    e = tr.export()
    assert e["emitted"] == 10 and e["dropped"] == 6
    assert len(e["records"]) == 4
    # ring keeps the NEWEST records
    assert e["records"][-1]["signature"] == ["f32[9]"]
    # drained: an immediate re-export is empty
    assert tr.export() is None
    # multi-window: the ledger invariant holds across windows too
    tot_emitted, tot_exported, tot_dropped = 10, 4, 6
    for n in (3, 7, 1):
        for i in range(n):
            tr.note_compile("g", [f"f32[{i},{n}]"])
        e = tr.export()
        tot_emitted += e["emitted"]
        tot_exported += len(e["records"])
        tot_dropped += e["dropped"]
    st = tr.stats()
    assert st["emitted"] == tot_emitted == 21
    assert st["dropped"] == tot_dropped
    assert st["emitted"] == st["exported"] + st["stored"] + st["dropped"]
    assert st["exported"] == tot_exported and st["stored"] == 0


def test_wrap_probed_cache_growth_path():
    """The jit cache-miss seam with a `_cache_size`-style probe: a call
    records a compile iff the cache grew across THAT call — signatures
    are only computed on actual misses."""
    tr = ct.CompileTracker(storm_threshold=0)
    cache = set()

    def fake_jit(x):
        cache.add((x.shape, str(x.dtype)))
        return x

    wrapped = tr.wrap(fake_jit, name="t.fn", probe=lambda: len(cache))
    wrapped(np.zeros((4,), np.float32))
    wrapped(np.zeros((4,), np.float32))      # cache hit: no record
    wrapped(np.zeros((5,), np.float32))      # miss: recompile
    st = tr.callable_stats("t.fn")
    assert st["compiles"] == 2 and st["recompiles"] == 1
    assert st["last_diff"] == ["arg[0]: f32[4] -> f32[5]"]
    assert tr.stats()["emitted"] == 2


def test_wrap_probeless_signature_novelty_path():
    """Without a probe the seam falls back to signature novelty — a
    repeated signature is a cache hit, a new one a compile."""
    tr = ct.CompileTracker(storm_threshold=0)
    wrapped = tr.wrap(lambda *a, **k: None, name="t.nov")
    wrapped(np.zeros((4,), np.float32))
    wrapped(np.zeros((4,), np.float32))
    wrapped(np.zeros((5,), np.float32), flag=True)
    st = tr.callable_stats("t.nov")
    assert st["compiles"] == 2 and st["recompiles"] == 1
    assert st["last_sig"] == ["f32[5]", "flag=bool"]


def test_wrap_attributes_inflight_monitoring_durations():
    """The thread-local attribution stack: /jax/core/compile/* phase
    durations reported DURING a wrapped call are folded into that
    call's record (measured_s/backend_s), and a backend_compile seen in
    flight marks the call compiled even when the probe saw no growth
    (exactly what jax's C++ dispatch cache does to a Python probe)."""
    tr = ct.CompileTracker(role="w", storm_threshold=0)
    ct.stop_global()

    def fn(x):
        # simulate jax.monitoring firing while the call is in flight
        ct._on_jax_duration("/jax/core/compile/jaxpr_trace_duration",
                            0.05)
        ct._on_jax_duration(
            "/jax/core/compile/backend_compile_duration", 0.125)
        ct._on_jax_duration("/jax/unrelated/event", 99.0)  # ignored
        return x

    wrapped = tr.wrap(fn, name="t.attr", probe=lambda: 0)  # no growth
    wrapped(np.zeros((2, 2), np.float32))
    e = tr.export()
    assert len(e["records"]) == 1
    rec = e["records"][0]
    assert rec["name"] == "t.attr"
    assert rec["backend_s"] == 0.125
    assert rec["measured_s"] == pytest.approx(0.175)
    assert rec["duration_s"] > 0


def test_unattributed_backend_compile_still_ringed():
    """An un-wrapped jit's backend compile (no call in flight) must not
    vanish: it lands as a nameless record so `compiles` shows it."""
    tr = ct.CompileTracker(storm_threshold=0)
    tr.note_monitor_duration("jaxpr_trace", 0.01)       # counted only
    tr.note_monitor_duration("backend_compile", 0.25)   # ringed
    tr.note_cache_miss()
    e = tr.export()
    assert len(e["records"]) == 1
    assert e["records"][0]["name"] == ""
    assert e["records"][0]["kind"] == "backend_compile"
    assert e["counts"]["jaxpr_trace"] == 1
    assert e["counts"]["backend_compile"] == 1
    assert e["counts"]["cache_miss"] == 1


def test_storm_once_per_excursion_and_rearm():
    """A recompile burst crossing the threshold journals EXACTLY ONE
    compile_storm; the detector re-arms only after the rate falls below
    half the threshold, so a sustained storm cannot spam the journal
    but a second excursion fires again."""
    tr = ct.CompileTracker(role="w", node="n1", worker="w1",
                           storm_threshold=5, storm_window_s=0.2)
    for i in range(8):                       # 7 recompiles in << 0.2s
        tr.note_compile("f", [f"f32[{i},4]"])
    evs = tr.drain_journal_events()
    assert len(evs) == 1, evs
    ev = evs[0]
    assert ev["type"] == "compile_storm" and ev["callable"] == "f"
    assert ev["recompiles"] >= 5 and ev["threshold"] == 5
    assert ev["diff"] and ev["worker"] == "w1"
    assert tr.stats()["storm_active"]
    # still inside the same excursion: more recompiles, no new event
    tr.note_compile("f", ["f32[99,4]"])
    assert tr.drain_journal_events() == []
    time.sleep(0.3)                          # window drains -> re-arm
    for i in range(8):
        tr.note_compile("f", [f"f32[{100 + i},4]"])
    evs = tr.drain_journal_events()
    assert len(evs) == 1 and evs[0]["type"] == "compile_storm"


def test_storm_disabled_at_zero_threshold():
    tr = ct.CompileTracker(storm_threshold=0, storm_window_s=0.2)
    for i in range(50):
        tr.note_compile("f", [f"f32[{i}]"])
    assert tr.drain_journal_events() == []
    assert not tr.stats()["storm_active"]


def test_stage_journal_event_stamps_identity():
    """Arbitrary staged events (the engine's invariant breach) carry
    the process identity without caller plumbing, and staging is
    bounded."""
    tr = ct.CompileTracker(role="worker", node="n1", worker="w1")
    tr.stage_journal_event("llm_compile_invariant_breach",
                           programs=4, budget=3)
    evs = tr.drain_journal_events()
    assert len(evs) == 1
    ev = evs[0]
    assert ev["type"] == "llm_compile_invariant_breach"
    assert ev["role"] == "worker" and ev["worker"] == "w1"
    assert ev["programs"] == 4 and ev["budget"] == 3
    for i in range(200):
        tr.stage_journal_event("e", i=i)
    assert len(tr.drain_journal_events()) == ct._MAX_JOURNAL


# ----------------------------------------------------------------- store

def _export_with(names_sigs, **kw):
    tr = ct.CompileTracker(storm_threshold=0, **kw)
    for name, sig in names_sigs:
        tr.note_compile(name, sig)
    return tr.export()


def test_compile_store_cursor_and_filters():
    s = ct.CompileStore()
    s.ingest("w1", _export_with([("llm.step", ["f32[8]"]),
                                 ("llm.step", ["f32[9]"])]),
             role="worker", node="nodeA", worker="w1")
    s.ingest("w2", _export_with([("train.full_step", ["f32[16,64]"])]),
             role="worker", node="nodeB", worker="w2")
    d = s.dump()
    assert len(d["records"]) == 3 and d["procs"] == 2
    seqs = [r["seq"] for r in d["records"]]
    assert seqs == sorted(seqs)
    # records are identity-stamped at ingest
    assert {r["worker"] for r in d["records"]} == {"w1", "w2"}
    # cursor: only records after last_seq on the next poll
    cur = d["last_seq"]
    assert s.dump(after_seq=cur)["records"] == []
    s.ingest("w1", _export_with([("llm.step", ["f32[10]"])]),
             role="worker", node="nodeA", worker="w1")
    follow = s.dump(after_seq=cur)["records"]
    assert len(follow) == 1 and follow[0]["signature"] == ["f32[10]"]
    # substring filters
    assert {r["worker"] for r in s.dump(worker="w2")["records"]} == \
        {"w2"}
    assert all("llm" in r["name"]
               for r in s.dump(callable="llm")["records"])
    assert {r["worker"] for r in s.dump(node="nodeB")["records"]} == \
        {"w2"}
    ron = s.dump(recompiles_only=True)["records"]
    assert len(ron) == 1 and ron[0]["diff"] == \
        ["arg[0]: f32[8] -> f32[9]"]
    # newest-N limit keeps the tail, follow-loop safe
    lim = s.dump(limit=2)["records"]
    assert len(lim) == 2 and lim[-1]["seq"] == s.dump()["last_seq"]


def test_compile_store_by_callable_aggregation():
    s = ct.CompileStore()
    s.ingest("w1", _export_with([("llm.step", ["f32[8]"]),
                                 ("llm.step", ["f32[9]"])]),
             role="worker", worker="w1")
    s.ingest("w2", _export_with([("llm.step", ["f32[8]"])]),
             role="worker", worker="w2")
    agg = s.dump(by_callable=True)["by_callable"]
    a = agg["llm.step"]
    assert a["compiles"] == 3 and a["recompiles"] == 1
    assert a["procs"] == 2
    assert a["last_diff"] == ["arg[0]: f32[8] -> f32[9]"]


def test_compile_store_lru_eviction_counts_drops():
    s = ct.CompileStore(max_procs=2)
    for i in range(3):
        s.ingest(f"w{i}", _export_with([(f"f{i}", ["f32[4]"])]),
                 worker=f"w{i}")
    d = s.dump()
    assert d["procs"] == 2
    # the evicted process's records joined the drop ledger exactly
    assert d["dropped_total"] == 1
    assert {r["worker"] for r in d["records"]} == {"w1", "w2"}
    # process-side ring drops are folded into the same ledger
    s.ingest("w9", _export_with([(f"g{i}", [f"f32[{i}]"])
                                 for i in range(10)], ring_records=4),
             worker="w9")
    assert s.dump()["dropped_total"] == 1 + 6 + 1  # +1: w1 evicted


# -------------------------------------------------------------- perfetto

def test_to_perfetto_multi_plane_schema():
    """The unified timeline: every plane lands in its own named lane
    (ph:'M' process_name metadata), span/compile events are ph:'X' on
    one microsecond wall clock, and the whole object round-trips JSON
    (what ui.perfetto.dev requires)."""
    from ray_tpu.runtime.events import to_perfetto

    now = 1000.0
    events = [
        {"name": "task_a", "kind": "task", "task_id": "t1",
         "start": now, "end": now + 0.5, "ok": True,
         "node": "nodeA", "worker": "w1", "trace_id": "abc"},
        {"name": "step", "kind": "train_step", "task_id": "tsp",
         "start": now, "end": now + 0.3, "ok": True},
        {"name": "forward", "kind": "train_phase", "task_id": "tsp",
         "start": now, "end": now + 0.1, "ok": True},
        {"name": "__dropped__", "kind": "meta", "start": 0, "end": 0},
    ]
    compiles = [
        {"ts": now + 2.0, "name": "llm.step", "duration_s": 1.5,
         "measured_s": 1.2, "worker": "w1", "recompile": True,
         "diff": ["arg[0]: f32[8] -> f32[9]"],
         "signature": ["f32[9]"], "fingerprint": "beef", "kind": "jit"},
        {"ts": now + 3.0, "name": "", "duration_s": 0.2, "pid": 77,
         "recompile": False, "signature": [], "kind": "backend_compile"},
    ]
    requests = [
        {"rid": "req-1", "t0_wall": now, "e2e": 0.8, "ttft": 0.2,
         "admits": [[0.05, 0]], "prompt_tokens": 16, "n_generated": 8,
         "finish_reason": "stop", "trace_id": "abc", "worker": "w1"},
        {"rid": "req-skipped"},  # no t0_wall: skipped, not crashed
    ]
    journal = [
        {"ts": now + 1.0, "type": "compile_storm", "seq": 1,
         "callable": "llm.step", "recompiles": 9,
         "diff": ["arg[0]: f32[8] -> f32[9]"]},
        {"type": "no_ts_skipped"},
    ]
    trace = to_perfetto(events, compiles=compiles, requests=requests,
                        journal=journal)
    json.loads(json.dumps(trace))  # ui.perfetto.dev ingests pure JSON
    evs = trace["traceEvents"]
    lanes = {e["args"]["name"] for e in evs
             if e.get("ph") == "M" and e.get("name") == "process_name"}
    assert lanes == {"spans: node nodeA", "train: steps + phases",
                     "llm: requests", "xla: compiles",
                     "journal: cluster events"}
    # distinct pids per lane: Perfetto renders them as separate tracks
    assert len({e["pid"] for e in evs}) >= 5
    assert not any(e.get("name") == "__dropped__" for e in evs)
    for e in evs:
        if e.get("ph") == "X":
            assert e["dur"] >= 0 and "ts" in e and "pid" in e

    rec = next(e for e in evs if e.get("name") == "RECOMPILE llm.step")
    assert rec["args"]["diff"] == ["arg[0]: f32[8] -> f32[9]"]
    assert rec["ts"] == pytest.approx((now + 2.0 - 1.5) * 1e6)
    assert rec["dur"] == pytest.approx(1.5 * 1e6)
    assert any(e.get("name") == "<unattributed>" for e in evs)
    assert any(e.get("name") == "first_token" and e.get("ph") == "i"
               for e in evs)
    assert any(e.get("name") == "queue_wait" for e in evs)
    storm = next(e for e in evs if e.get("name") == "compile_storm")
    assert storm["ph"] == "i" and storm["s"] == "g"
    assert storm["args"]["callable"] == "llm.step"
    assert sum(1 for e in evs if e.get("cat") == "journal") == 1


# ------------------------------------------------------------------- e2e

@pytest.fixture(scope="module")
def two_node_compiled():
    import ray_tpu as rt
    rt.init(num_cpus=1, resources={"n1": 1.0}, _system_config={
        "object_store_memory_bytes": 64 * MiB,
        "metrics_export_period_s": 0.2,
        "compile_storm_threshold": 5,   # 8 shapes -> 7 recompiles: fires
        "compile_storm_window_s": 30.0,
    })
    from ray_tpu.core.worker import global_worker
    from ray_tpu.runtime.cluster_backend import start_node
    backend = global_worker.backend
    session = backend.head.call("connect_driver", {})["session"]
    proc = start_node(backend.head_addr, session,
                      resources={"CPU": 1.0, "n2": 1.0})
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            raise RuntimeError(f"second node exited rc={proc.returncode}")
        nodes = backend.head.call("list_nodes")
        if sum(1 for n in nodes if n["alive"]) >= 2:
            break
        time.sleep(0.2)
    else:
        raise RuntimeError("second node never registered")
    yield rt, backend, session
    proc.terminate()
    try:
        proc.wait(timeout=10)
    finally:
        rt.shutdown()


def _compiles_until(head, payload, pred, timeout=90):
    deadline = time.monotonic() + timeout
    d = {"records": []}
    while time.monotonic() < deadline:
        d = head.call("compiles_dump", dict(payload), timeout=10)
        if pred(d):
            return d
        time.sleep(0.3)
    return d


def _metric_sum(head, name):
    snap = head.call("metrics_dump", {}, timeout=10) or {}
    entry = snap.get(name) or {}
    total = 0.0
    for v in (entry.get("values") or {}).values():
        if isinstance(v, (int, float)):
            total += v
        elif isinstance(v, dict):
            total += sum(x for x in v.values()
                         if isinstance(x, (int, float)))
    return total


def test_shape_unstable_fn_lands_records_at_head(two_node_compiled):
    """The acceptance scenario: a shape-unstable jitted function run on
    BOTH nodes produces per-process compile records with signature
    diffs at the head, xla_recompiles_total increments, and each
    process's excursion raises exactly one compile_storm."""
    rt_, backend, _session = two_node_compiled
    head = backend.head

    @rt_.remote(num_cpus=1)
    def unstable(tag):
        import jax
        import jax.numpy as jnp
        from ray_tpu.util import compile_tracker
        tr = compile_tracker.get_global()
        assert tr is not None, "worker bootstrap did not start tracker"
        f = tr.wrap(jax.jit(lambda x: x * 2 + 1),
                    name=f"e2e.unstable_{tag}")
        for i in range(8):   # 8 shapes: 7 recompiles > threshold 5
            f(jnp.zeros((i + 1,), jnp.float32))
        return tr.stats()["counts"]

    # one task pinned to each node (n1/n2 custom resources), so the
    # records provably come from two distinct processes on two nodes
    futs = [unstable.options(resources={"n2": 1.0}).remote("b"),
            unstable.options(resources={"n1": 1.0}).remote("a")]
    counts_b, counts_a = rt_.get(futs, timeout=300)
    for c in (counts_a, counts_b):
        assert c.get("jit", 0) >= 8 and c.get("recompile", 0) >= 7, c

    # wait until BOTH processes' full windows landed (records stream
    # across several telemetry flushes)
    def _complete(d):
        agg = d.get("by_callable") or {}
        return {"e2e.unstable_a", "e2e.unstable_b"} <= set(agg) \
            and all(a["compiles"] >= 8 for a in agg.values())

    d = _compiles_until(
        head, {"callable": "e2e.unstable", "by_callable": True},
        _complete)
    workers = {r["worker"] for r in d["records"]}
    assert len(workers) >= 2, (workers, len(d["records"]))
    recompiles = [r for r in d["records"] if r["recompile"]]
    assert recompiles, d["records"][:3]
    for r in recompiles:
        assert r["diff"] and "->" in r["diff"][0], r
        assert r["signature"] and r["role"] == "worker", r
    # per-callable aggregation attributes recompiles to both tasks
    agg = d["by_callable"]
    assert {"e2e.unstable_a", "e2e.unstable_b"} <= set(agg), agg
    assert all(a["recompiles"] >= 7 for a in agg.values()), agg

    # the metric plane saw the recompiles too
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        if _metric_sum(head, "xla_recompiles_total") >= 14:
            break
        time.sleep(0.3)
    assert _metric_sum(head, "xla_recompiles_total") >= 14
    assert _metric_sum(head, "xla_compiles_total") > 0

    # exactly one compile_storm per process excursion (two processes;
    # one if the scheduler reused a single worker for both tasks)
    deadline = time.monotonic() + 60
    storms = []
    while time.monotonic() < deadline:
        storms = head.call("events_dump", {"type": "compile_storm"},
                           timeout=10)
        if len(storms) >= len(workers):
            break
        time.sleep(0.3)
    assert 1 <= len(storms) <= len(workers), storms
    for s in storms:
        assert s["callable"].startswith("e2e.unstable"), s
        assert s["recompiles"] >= 5 and s["diff"], s


def test_perfetto_export_unifies_planes_e2e(two_node_compiled,
                                            tmp_path):
    """`trace --perfetto out.json` against the live 2-node cluster
    writes one file whose compile, task-span and train-phase lanes
    share a clock (the ISSUE's acceptance artifact)."""
    import io
    from contextlib import redirect_stdout

    from ray_tpu.scripts import cli

    rt_, backend, _session = two_node_compiled
    head = backend.head
    address = backend.head_addr

    @rt_.remote(num_cpus=1)
    def compiled_span():
        import jax
        import jax.numpy as jnp
        from ray_tpu.util import compile_tracker
        tr = compile_tracker.get_global()
        f = tr.wrap(jax.jit(lambda x: x + 1), name="e2e.span_fn")
        f(jnp.zeros((3,), jnp.float32))
        return True

    assert rt_.get(compiled_span.remote(), timeout=300)
    # train lane: seed authentic train_step/train_phase spans (the
    # profiler's wire shape) through the same telemetry path
    now = time.time()
    head.call("telemetry_push", {
        "worker": "traincliw" + "0" * 23, "node": "trainnode" + "0" * 23,
        "events": [
            {"name": "train_step", "kind": "train_step", "task_id": "p",
             "start": now - 0.4, "end": now - 0.1, "ok": True},
            {"name": "forward", "kind": "train_phase", "task_id": "p",
             "start": now - 0.4, "end": now - 0.3, "ok": True},
        ]}, timeout=10)
    # wait for the task span AND its compile record to reach the head
    _compiles_until(head, {"callable": "e2e.span_fn"},
                    lambda d: bool(d["records"]))
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        ev = head.call("timeline_dump") or []
        if any(e.get("kind") == "train_phase" for e in ev) and \
                any(e.get("kind") not in ("train_step", "train_phase")
                    for e in ev):
            break
        time.sleep(0.3)

    out = tmp_path / "cluster.perfetto.json"
    buf = io.StringIO()
    with redirect_stdout(buf):
        assert cli.main(["trace", "--perfetto", str(out),
                         "--address", address]) == 0
    assert "lanes" in buf.getvalue()
    with open(out) as f:
        trace = json.load(f)
    evs = trace["traceEvents"]
    lanes = {e["args"]["name"] for e in evs
             if e.get("ph") == "M" and e.get("name") == "process_name"}
    assert "xla: compiles" in lanes, lanes
    assert "train: steps + phases" in lanes, lanes
    assert any(name.startswith("spans: node") for name in lanes), lanes
    assert any(e.get("cat") == "xla_compile" and
               e.get("name") == "e2e.span_fn" for e in evs
               if e.get("ph") == "X")
    assert any(e.get("cat") == "train_phase" for e in evs)
