"""Multiprocess cluster runtime tests.

Coverage model mirrors the reference's core test suite (reference:
python/ray/tests/test_basic.py, test_actor_failures.py,
test_object_store.py, test_multi_node.py) run against the real runtime:
head + node daemon + worker processes, objects through the C++ shm store,
process kills for fault-tolerance paths.
"""

import os
import signal
import sys
import time
import uuid

import numpy as np
import pytest

import ray_tpu as rt
from ray_tpu.core.worker import global_worker


@pytest.fixture(scope="module")
def cluster_rt():
    rt.init(num_cpus=4, _system_config={
        "object_store_memory_bytes": 128 * 1024 * 1024,
        "worker_pool_prestart": 2,
        "health_check_period_ms": 200,
        "health_check_timeout_ms": 1500,
    })
    yield rt
    rt.shutdown()


# ---------------------------------------------------------------- tasks


def test_task_roundtrip(cluster_rt):
    @rt.remote
    def add(a, b, scale=1):
        return (a + b) * scale

    assert rt.get(add.remote(1, 2), timeout=60) == 3
    assert rt.get(add.remote(1, 2, scale=10), timeout=30) == 30


def test_parallel_tasks(cluster_rt):
    @rt.remote
    def slp(i):
        time.sleep(0.4)
        return i

    t0 = time.monotonic()
    out = rt.get([slp.remote(i) for i in range(4)], timeout=60)
    dt = time.monotonic() - t0
    assert out == [0, 1, 2, 3]
    # 4 x 0.4s sleeps must overlap across worker processes
    assert dt < 1.3, f"tasks did not run in parallel: {dt:.2f}s"


def test_parallel_burst_without_cached_leases(cluster_rt):
    """A burst submitted while NO lease is cached must still fan out.

    Regression: transport-level task batching once packed a whole queued
    burst onto the FIRST granted lease, serializing onto one worker what
    belonged on four (a lease is a concurrency slot — the bug survived
    test_parallel_tasks because a warm cached lease changes the timing).
    """
    @rt.remote
    def slp(i):
        time.sleep(0.5)
        return i

    @rt.remote
    def noop(i):
        return i

    # warm the worker POOL to 4 processes (spawn costs seconds on a 1-CPU
    # host and is not what this test measures)...
    rt.get([slp.options(name="warm").remote(i) for i in range(4)],
           timeout=60)
    # ...then let the cached idle leases reap (lease_idle_linger_s=0.5):
    # the workers stay pooled but every task in the next burst depends on
    # a fresh lease grant
    time.sleep(1.2)
    t0 = time.monotonic()
    out = rt.get([slp.remote(i) for i in range(4)], timeout=60)
    dt = time.monotonic() - t0
    assert out == [0, 1, 2, 3]
    assert dt < 1.8, f"burst did not run in parallel: {dt:.2f}s"


def test_large_object_via_shm(cluster_rt):
    arr = np.arange(500_000, dtype=np.float64)
    ref = rt.put(arr)
    oid = ref.id()
    # big values must be sealed in the shm store, not the memory store
    assert global_worker.backend.object_plane.store.contains(oid.binary())
    back = rt.get(ref, timeout=30)
    assert np.array_equal(arr, back)


def test_ref_args_and_nested_refs(cluster_rt):
    @rt.remote
    def double(x):
        return x * 2

    @rt.remote
    def sum_list(refs):
        return sum(rt.get(refs))

    a = rt.put(np.ones(200_000))  # shm-sized
    b = double.remote(a)
    assert float(rt.get(b, timeout=30).sum()) == 400_000.0
    # nested refs inside an inline list argument
    small = [rt.put(i) for i in range(5)]
    assert rt.get(sum_list.remote(small), timeout=30) == 10


def test_task_error_propagation(cluster_rt):
    @rt.remote
    def boom():
        raise ValueError("kapow-task")

    with pytest.raises(Exception, match="kapow-task"):
        rt.get(boom.remote(), timeout=30)


def test_nested_task_submission(cluster_rt):
    @rt.remote
    def inner(x):
        return x + 1

    @rt.remote
    def outer(x):
        return rt.get(inner.remote(x), timeout=30) + 100

    assert rt.get(outer.remote(1), timeout=60) == 102


def test_wait(cluster_rt):
    @rt.remote
    def fast():
        return "fast"

    @rt.remote
    def slow():
        time.sleep(2.0)
        return "slow"

    f, s = fast.remote(), slow.remote()
    ready, pending = rt.wait([f, s], num_returns=1, timeout=10)
    assert ready == [f] and pending == [s]
    assert rt.get(s, timeout=30) == "slow"


def test_refcount_frees_shm_object(cluster_rt):
    arr = np.arange(300_000, dtype=np.float64)
    ref = rt.put(arr)
    key = ref.id().binary()
    store = global_worker.backend.object_plane.store
    rt.get(ref, timeout=30)
    assert store.contains(key)
    del ref
    deadline = time.monotonic() + 10
    while store.contains(key) and time.monotonic() < deadline:
        time.sleep(0.05)
    assert not store.contains(key), "shm object not freed after last ref died"


# ---------------------------------------------------------------- actors


def test_actor_ordered_state(cluster_rt):
    @rt.remote
    class Counter:
        def __init__(self, start=0):
            self.v = start

        def inc(self, d=1):
            self.v += d
            return self.v

    c = Counter.remote(10)
    out = rt.get([c.inc.remote() for _ in range(5)], timeout=60)
    assert out == [11, 12, 13, 14, 15]


def test_named_actor(cluster_rt):
    @rt.remote
    class KV:
        def __init__(self):
            self.d = {}

        def set(self, k, v):
            self.d[k] = v
            return True

        def get(self, k):
            return self.d.get(k)

    name = f"kv-{uuid.uuid4().hex[:6]}"
    a = KV.options(name=name).remote()
    rt.get(a.set.remote("x", 42), timeout=60)
    h = rt.get_actor(name)
    assert rt.get(h.get.remote("x"), timeout=30) == 42
    with pytest.raises(ValueError):
        rt.get_actor("no-such-actor")


def test_actor_handle_in_task(cluster_rt):
    @rt.remote
    class Acc:
        def __init__(self):
            self.v = 0

        def add(self, d):
            self.v += d
            return self.v

    @rt.remote
    def bump(handle, n):
        return rt.get([handle.add.remote(1) for _ in range(n)], timeout=30)

    a = Acc.remote()
    assert rt.get(bump.remote(a, 3), timeout=60) == [1, 2, 3]


def test_actor_creation_error(cluster_rt):
    @rt.remote
    class Broken:
        def __init__(self):
            raise RuntimeError("ctor-fail")

        def m(self):
            return 1

    b = Broken.remote()
    with pytest.raises(rt.exceptions.ActorDiedError, match="ctor-fail"):
        rt.get(b.m.remote(), timeout=60)


def test_actor_method_error(cluster_rt):
    @rt.remote
    class Bad:
        def boom(self):
            raise ValueError("kapow-actor")

        def fine(self):
            return "ok"

    b = Bad.remote()
    with pytest.raises(Exception, match="kapow-actor"):
        rt.get(b.boom.remote(), timeout=60)
    # actor survives an application error
    assert rt.get(b.fine.remote(), timeout=30) == "ok"


def test_kill_actor(cluster_rt):
    @rt.remote
    class P:
        def pid(self):
            return os.getpid()

    p = P.remote()
    rt.get(p.pid.remote(), timeout=60)
    rt.kill(p)
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline:
        try:
            rt.get(p.pid.remote(), timeout=10)
            time.sleep(0.1)
        except rt.exceptions.ActorDiedError:
            return
    pytest.fail("kill() never surfaced ActorDiedError")


# ------------------------------------------------------- fault tolerance


def test_worker_crash_surfaces(cluster_rt):
    @rt.remote(max_retries=0)
    def die():
        os._exit(1)

    with pytest.raises(rt.exceptions.WorkerCrashedError):
        rt.get(die.remote(), timeout=60)


def test_task_retry_on_worker_death(cluster_rt):
    marker = f"/tmp/rtpu_flaky_{uuid.uuid4().hex[:8]}"

    @rt.remote(max_retries=2)
    def flaky(path):
        if not os.path.exists(path):
            open(path, "w").close()
            os._exit(1)
        return "recovered"

    try:
        assert rt.get(flaky.remote(marker), timeout=90) == "recovered"
    finally:
        if os.path.exists(marker):
            os.unlink(marker)


def test_retry_survives_corpse_leases(cluster_rt):
    """Deterministic corpse-window test: kill a pooled worker BEFORE
    submitting, so early leases deterministically name a dead address.
    With per-distinct-address retry accounting + the dead-addr grant
    filter (reference semantics: owner max_retries counts executions,
    task_manager.h:219), max_retries=1 tasks must all still succeed —
    repeated pushes into one corpse must not burn the budget."""
    @rt.remote
    def whoami():
        return os.getpid()

    # warm the pool and learn a victim pid
    pids = set(rt.get([whoami.remote() for _ in range(4)], timeout=90))
    victim = next(iter(pids))
    os.kill(victim, signal.SIGKILL)
    # no settling sleep: submitting IMMEDIATELY is the point — some of
    # these tasks race into the corpse's still-cached leases
    @rt.remote(max_retries=1)
    def ping(i):
        return i * 2

    out = rt.get([ping.remote(i) for i in range(16)], timeout=120)
    assert out == [i * 2 for i in range(16)]


def test_actor_restart_and_exhaustion(cluster_rt):
    @rt.remote(max_restarts=1)
    class Svc:
        def __init__(self):
            self.n = 0

        def pid(self):
            return os.getpid()

        def inc(self):
            self.n += 1
            return self.n

    s = Svc.remote()
    pid1 = rt.get(s.pid.remote(), timeout=60)
    assert rt.get(s.inc.remote(), timeout=30) == 1
    os.kill(pid1, signal.SIGKILL)

    # restarted instance: fresh state, new pid
    val, pid2 = None, None
    deadline = time.monotonic() + 45
    while time.monotonic() < deadline:
        try:
            val = rt.get(s.inc.remote(), timeout=15)
            pid2 = rt.get(s.pid.remote(), timeout=15)
            break
        except rt.exceptions.ActorError:
            time.sleep(0.2)
    assert pid2 is not None and pid2 != pid1
    assert val == 1, "restart must reset actor state"

    # second kill exhausts max_restarts=1 -> permanently dead
    os.kill(pid2, signal.SIGKILL)
    deadline = time.monotonic() + 45
    while time.monotonic() < deadline:
        try:
            rt.get(s.pid.remote(), timeout=15)
            time.sleep(0.2)
        except rt.exceptions.ActorDiedError:
            return
    pytest.fail("actor never became DEAD after exhausting restarts")


def test_chaos_rpc_injection_retries(cluster_rt):
    """First push_task call is chaos-failed; the lease-retry path recovers
    (reference: rpc_chaos.h:23 RAY_testing_rpc_failure)."""
    from ray_tpu.core.config import GlobalConfig
    from ray_tpu.runtime import protocol

    @rt.remote(max_retries=3)
    def ok():
        return "survived"

    GlobalConfig.apply({"testing_rpc_failure": "push_task=1"})
    protocol.reset_chaos()
    try:
        assert rt.get(ok.remote(), timeout=60) == "survived"
    finally:
        GlobalConfig.apply({"testing_rpc_failure": ""})
        protocol.reset_chaos()


# ------------------------------------------------------------ state APIs


def test_cluster_state_apis(cluster_rt):
    res = rt.cluster_resources()
    assert res.get("CPU") == 4.0
    nodes = rt.nodes()
    assert len(nodes) == 1 and nodes[0]["Alive"]
    avail = rt.available_resources()
    assert avail.get("CPU", 0) <= res["CPU"]
    dump = global_worker.backend.state_dump()
    assert "actors" in dump and "nodes" in dump
