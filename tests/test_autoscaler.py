"""Autoscaler tests (reference scope: autoscaler v2 reconciler +
cluster_utils.AutoscalingCluster over the fake node provider)."""

import time

import pytest

import ray_tpu as rt
from ray_tpu.autoscaler import Autoscaler, AutoscalingCluster


def test_bin_packing_counts_nodes():
    a = Autoscaler.__new__(Autoscaler)
    a.node_type = {"CPU": 2.0}
    # 3 x 1-CPU shapes fit in 2 nodes; a 4-CPU shape can never fit
    assert a._nodes_needed([{"CPU": 1.0}] * 3) == 2
    assert a._nodes_needed([{"CPU": 4.0}]) == 0
    assert a._nodes_needed([]) == 0
    assert a._nodes_needed([{"CPU": 2.0}, {"CPU": 2.0}]) == 2


def test_scale_up_then_down():
    cluster = AutoscalingCluster(
        head_resources={"CPU": 1.0},
        worker_node_type={"CPU": 2.0},
        max_workers=2,
        idle_timeout_s=6.0)
    try:
        rt.init(address=cluster.address, _system_config={
            "infeasible_grace_s": 60.0,
        })

        @rt.remote(num_cpus=2)
        def heavy(i):
            time.sleep(1.0)
            return i

        # head node has 1 CPU: these shapes are infeasible until the
        # autoscaler reacts to the recorded demand
        t0 = time.monotonic()
        out = rt.get([heavy.remote(i) for i in range(4)], timeout=120)
        assert sorted(out) == [0, 1, 2, 3]
        assert len(rt.nodes()) >= 2, "no worker node was launched"

        # drain: nodes idle past the timeout must be terminated
        deadline = time.monotonic() + 45
        while time.monotonic() < deadline:
            alive = [n for n in rt.nodes() if n["Alive"]]
            if len(alive) == 1:
                break
            time.sleep(0.5)
        alive = [n for n in rt.nodes() if n["Alive"]]
        assert len(alive) == 1, f"idle nodes never scaled down: {alive}"
        rt.shutdown()
    finally:
        try:
            rt.shutdown()
        except Exception:
            pass
        cluster.shutdown()


def test_tpu_slice_gang_scale_up_and_drain():
    """A pending STRICT_PACK slice-head PG drives ONE slice creation
    through the (mocked) GCE TPU API; once the slice 'joins' and the PG
    is removed, idle drain deletes the slice via the API (VERDICT #6
    done-criterion; reference: autoscaler/_private/gcp/node_provider.py)."""
    from ray_tpu.providers.gcp_tpu import TpuVmNodeProvider
    from ray_tpu.runtime.cluster_backend import start_head, start_node
    from ray_tpu.runtime.protocol import RpcClient, RpcError
    from ray_tpu.util.placement_group import (placement_group,
                                              remove_placement_group)
    import os

    class FakeGceHttp:
        def __init__(self):
            self.requests = []

        def request(self, method, url, body=None):
            self.requests.append((method, url, body))
            return {"name": "operations/fake-op", "done": True}

    session = os.urandom(4).hex()
    head_proc, address = start_head(session)
    static_node = start_node(address, session, resources={"CPU": 1.0})
    probe = RpcClient(address, name="gang-test")
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        try:
            if any(n["alive"] for n in probe.call("list_nodes", timeout=5)):
                break
        except RpcError:
            pass
        time.sleep(0.1)

    fake = FakeGceHttp()
    provider = TpuVmNodeProvider(
        project="proj", zone="us-central2-b",
        accelerator_type="v5litepod-8", runtime_version="tpu-ubuntu2204",
        head_addr=address, session=session, http=fake)
    slice_shape = TpuVmNodeProvider.slice_node_type("v5litepod-8")
    scaler = Autoscaler(address, provider, node_type=slice_shape,
                        max_workers=1, idle_timeout_s=2.0,
                        poll_period_s=0.3).start()
    joined = None
    try:
        rt.init(address=address,
                _system_config={"infeasible_grace_s": 60.0})
        pg = placement_group([{"TPU-v5e-8-head": 1}],
                             strategy="STRICT_PACK")
        # pending gang bundle -> exactly one slice-create API call
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline and not fake.requests:
            time.sleep(0.1)
        creates = [r for r in fake.requests if r[0] == "POST"]
        assert len(creates) == 1, fake.requests
        method, url, body = creates[0]
        assert "tpu.googleapis.com" in url and "nodes?nodeId=rtpu-" in url
        assert body["acceleratorType"] == "v5litepod-8"
        assert address in body["metadata"]["startup-script"]
        # capped at max_workers: no second create even while pending
        time.sleep(1.0)
        assert len([r for r in fake.requests if r[0] == "POST"]) == 1

        # 'slice boots': stand in for the TPU VM with a local daemon that
        # registers under the provisioned node identity + slice resources
        node_id = scaler._handles[0].rtpu_node_id
        joined = start_node(address, session, resources=slice_shape,
                            node_id=node_id)
        assert pg.wait(30), "gang PG never placed on the joined slice"
        remove_placement_group(pg)

        # idle past the timeout -> the slice is RELEASED via the API
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if any(r[0] == "DELETE" for r in fake.requests):
                break
            time.sleep(0.2)
        deletes = [r for r in fake.requests if r[0] == "DELETE"]
        assert len(deletes) == 1, fake.requests
        assert deletes[0][1].endswith(url.split("?nodeId=")[1]), deletes
    finally:
        rt.shutdown()
        scaler.stop()
        probe.close()
        for proc in (joined, static_node, head_proc):
            if proc is None:
                continue
            try:
                proc.terminate()
                proc.wait(timeout=5)
            except Exception:
                proc.kill()
