"""Autoscaler tests (reference scope: autoscaler v2 reconciler +
cluster_utils.AutoscalingCluster over the fake node provider)."""

import time

import pytest

import ray_tpu as rt
from ray_tpu.autoscaler import Autoscaler, AutoscalingCluster


def test_bin_packing_counts_nodes():
    from ray_tpu.autoscaler import NodeTypeSpec
    a = Autoscaler.__new__(Autoscaler)
    a.node_types = {"cpu": NodeTypeSpec({"CPU": 2.0}, max_workers=8)}
    # 3 x 1-CPU shapes fit in 2 nodes; a 4-CPU shape can never fit
    assert a._nodes_needed([{"CPU": 1.0}] * 3) == {"cpu": 2}
    assert a._nodes_needed([{"CPU": 4.0}]) == {}
    assert a._nodes_needed([]) == {}
    assert a._nodes_needed([{"CPU": 2.0}, {"CPU": 2.0}]) == {"cpu": 2}


def test_bin_packing_heterogeneous_catalog():
    """Mixed demand bin-packs across a catalog (VERDICT r4 #6; reference:
    resource_demand_scheduler.py:102): CPU tasks open CPU hosts (best
    fit), gang bundles open exactly the slice shape that fits them,
    per-type max_workers caps planning, and a quiet type drains
    independently (covered by _reconcile's per-type quiet list)."""
    from ray_tpu.autoscaler import NodeTypeSpec
    a = Autoscaler.__new__(Autoscaler)
    v5e8 = {"TPU": 8.0, "CPU": 4.0, "TPU-v5e-8-head": 1.0}
    v5e16 = {"TPU": 16.0, "CPU": 8.0, "TPU-v5e-16-head": 1.0}
    a.node_types = {
        "cpu": NodeTypeSpec({"CPU": 4.0}, max_workers=4),
        "v5e-8": NodeTypeSpec(v5e8, max_workers=2),
        "v5e-16": NodeTypeSpec(v5e16, max_workers=2),
    }
    # pure CPU demand never opens a slice
    assert a._nodes_needed([{"CPU": 1.0}] * 6) == {"cpu": 2}
    # a small gang bundle picks the SMALL slice; a big one the big slice
    assert a._nodes_needed([{"TPU-v5e-8-head": 1.0}]) == {"v5e-8": 1}
    assert a._nodes_needed([{"TPU-v5e-16-head": 1.0}]) == {"v5e-16": 1}
    # mixed wave: right mix — one bin per gang, CPU tasks packed into
    # the cpu host AND the slices' spare CPUs (true bin-packing: a slice
    # host's free CPUs absorb CPU tasks before a second host opens)
    need = a._nodes_needed(
        [{"CPU": 2.0}, {"TPU-v5e-8-head": 1.0}, {"CPU": 2.0},
         {"TPU-v5e-16-head": 1.0}, {"CPU": 2.0}])
    assert need == {"cpu": 1, "v5e-8": 1, "v5e-16": 1}, need
    # plain chip demand prefers the slice it wastes least of
    assert a._nodes_needed([{"TPU": 8.0}]) == {"v5e-8": 1}
    # per-type cap: live + planned never exceeds max_workers
    need = a._nodes_needed([{"TPU-v5e-8-head": 1.0}] * 5,
                           live={"v5e-8": 1})
    assert need == {"v5e-8": 1}, need


def test_scale_up_then_down():
    cluster = AutoscalingCluster(
        head_resources={"CPU": 1.0},
        worker_node_type={"CPU": 2.0},
        max_workers=2,
        idle_timeout_s=6.0)
    try:
        rt.init(address=cluster.address, _system_config={
            "infeasible_grace_s": 60.0,
        })

        @rt.remote(num_cpus=2)
        def heavy(i):
            time.sleep(1.0)
            return i

        # head node has 1 CPU: these shapes are infeasible until the
        # autoscaler reacts to the recorded demand
        t0 = time.monotonic()
        out = rt.get([heavy.remote(i) for i in range(4)], timeout=120)
        assert sorted(out) == [0, 1, 2, 3]
        assert len(rt.nodes()) >= 2, "no worker node was launched"

        # drain: nodes idle past the timeout must be terminated. Pure
        # poll-with-deadline — the budget covers idle_timeout_s plus the
        # driver's fast-lease pool idle-drain (a pooled lease keeps the
        # worker non-idle until it drains back), with headroom for a
        # loaded CI host. Assert on the poll's own final observation —
        # re-reading after the loop could race a node flap.
        deadline = time.monotonic() + 90
        alive = [n for n in rt.nodes() if n["Alive"]]
        while time.monotonic() < deadline and len(alive) != 1:
            time.sleep(0.5)
            alive = [n for n in rt.nodes() if n["Alive"]]
        assert len(alive) == 1, f"idle nodes never scaled down: {alive}"
        rt.shutdown()
    finally:
        try:
            rt.shutdown()
        except Exception:
            pass
        cluster.shutdown()


def test_heterogeneous_mixed_demand_end_to_end():
    """One Autoscaler over a CPU-host + TPU-slice catalog: a mixed wave
    (CPU tasks + a gang PG) launches the right node mix, and each type
    drains independently once its demand clears (VERDICT r4 #6
    done-criterion)."""
    import os

    from ray_tpu.autoscaler import LocalNodeProvider, NodeTypeSpec
    from ray_tpu.runtime.cluster_backend import start_head, start_node
    from ray_tpu.runtime.protocol import RpcClient, RpcError
    from ray_tpu.util.placement_group import (placement_group,
                                              remove_placement_group)

    session = os.urandom(4).hex()
    head_proc, address = start_head(session)
    static_node = start_node(address, session, resources={"CPU": 1.0})
    probe = RpcClient(address, name="hetero-test")
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        try:
            if any(n["alive"] for n in probe.call("list_nodes", timeout=5)):
                break
        except RpcError:
            pass
        time.sleep(0.1)

    slice_shape = {"TPU": 8.0, "CPU": 4.0, "TPU-v5e-8-head": 1.0}
    provider = LocalNodeProvider(address, session)
    scaler = Autoscaler(
        address, provider,
        node_types={
            "cpu": NodeTypeSpec({"CPU": 2.0}, max_workers=2),
            "v5e-8": NodeTypeSpec(slice_shape, max_workers=1),
        },
        idle_timeout_s=3.0, poll_period_s=0.3).start()
    try:
        rt.init(address=address,
                _system_config={"infeasible_grace_s": 60.0})

        @rt.remote(num_cpus=2)
        def heavy(i):
            time.sleep(0.5)
            return i

        pg = placement_group([{"TPU-v5e-8-head": 1}],
                             strategy="STRICT_PACK")
        out = rt.get([heavy.remote(i) for i in range(4)], timeout=120)
        assert sorted(out) == [0, 1, 2, 3]
        assert pg.wait(60), "gang bundle never placed"
        # the right MIX: at least one cpu node and exactly one slice
        types = {t for t, _ in scaler._handles}
        assert "cpu" in types and "v5e-8" in types, scaler._handles
        slice_nodes = [n for n in rt.nodes() if n["Alive"]
                       and n["Resources"].get("TPU-v5e-8-head")]
        assert len(slice_nodes) == 1, slice_nodes

        # demand clears -> BOTH types drain back to their min (0)
        remove_placement_group(pg)
        deadline = time.monotonic() + 45
        while time.monotonic() < deadline:
            alive = [n for n in rt.nodes() if n["Alive"]]
            if len(alive) == 1:   # only the static head node remains
                break
            time.sleep(0.5)
        alive = [n for n in rt.nodes() if n["Alive"]]
        assert len(alive) == 1, \
            f"idle nodes never scaled down: {[n['Resources'] for n in alive]}"
    finally:
        try:
            rt.shutdown()
        except Exception:
            pass
        scaler.stop()
        probe.close()
        for proc in (static_node, head_proc):
            try:
                proc.terminate()
                proc.wait(timeout=5)
            except Exception:
                proc.kill()


def test_tpu_slice_gang_scale_up_and_drain():
    """A pending STRICT_PACK slice-head PG drives ONE slice creation
    through the (mocked) GCE TPU API; once the slice 'joins' and the PG
    is removed, idle drain deletes the slice via the API (VERDICT #6
    done-criterion; reference: autoscaler/_private/gcp/node_provider.py)."""
    from ray_tpu.providers.gcp_tpu import TpuVmNodeProvider
    from ray_tpu.runtime.cluster_backend import start_head, start_node
    from ray_tpu.runtime.protocol import RpcClient, RpcError
    from ray_tpu.util.placement_group import (placement_group,
                                              remove_placement_group)
    import os

    class FakeGceHttp:
        def __init__(self):
            self.requests = []

        def request(self, method, url, body=None):
            self.requests.append((method, url, body))
            return {"name": "operations/fake-op", "done": True}

    session = os.urandom(4).hex()
    head_proc, address = start_head(session)
    static_node = start_node(address, session, resources={"CPU": 1.0})
    probe = RpcClient(address, name="gang-test")
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        try:
            if any(n["alive"] for n in probe.call("list_nodes", timeout=5)):
                break
        except RpcError:
            pass
        time.sleep(0.1)

    fake = FakeGceHttp()
    provider = TpuVmNodeProvider(
        project="proj", zone="us-central2-b",
        accelerator_type="v5litepod-8", runtime_version="tpu-ubuntu2204",
        head_addr=address, session=session, http=fake)
    slice_shape = TpuVmNodeProvider.slice_node_type("v5litepod-8")
    scaler = Autoscaler(address, provider, node_type=slice_shape,
                        max_workers=1, idle_timeout_s=2.0,
                        poll_period_s=0.3).start()
    joined = None
    try:
        rt.init(address=address,
                _system_config={"infeasible_grace_s": 60.0})
        pg = placement_group([{"TPU-v5e-8-head": 1}],
                             strategy="STRICT_PACK")
        # pending gang bundle -> exactly one slice-create API call
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline and not fake.requests:
            time.sleep(0.1)
        creates = [r for r in fake.requests if r[0] == "POST"]
        assert len(creates) == 1, fake.requests
        method, url, body = creates[0]
        assert "tpu.googleapis.com" in url and "nodes?nodeId=rtpu-" in url
        assert body["acceleratorType"] == "v5litepod-8"
        assert address in body["metadata"]["startup-script"]
        # capped at max_workers: no second create even while pending
        time.sleep(1.0)
        assert len([r for r in fake.requests if r[0] == "POST"]) == 1

        # 'slice boots': stand in for the TPU VM with a local daemon that
        # registers under the provisioned node identity + slice resources
        node_id = scaler._handles[0][1].rtpu_node_id
        joined = start_node(address, session, resources=slice_shape,
                            node_id=node_id)
        assert pg.wait(30), "gang PG never placed on the joined slice"
        remove_placement_group(pg)

        # idle past the timeout -> the slice is RELEASED via the API
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if any(r[0] == "DELETE" for r in fake.requests):
                break
            time.sleep(0.2)
        deletes = [r for r in fake.requests if r[0] == "DELETE"]
        assert len(deletes) == 1, fake.requests
        assert deletes[0][1].endswith(url.split("?nodeId=")[1]), deletes
    finally:
        rt.shutdown()
        scaler.stop()
        probe.close()
        for proc in (joined, static_node, head_proc):
            if proc is None:
                continue
            try:
                proc.terminate()
                proc.wait(timeout=5)
            except Exception:
                proc.kill()
