"""Autoscaler tests (reference scope: autoscaler v2 reconciler +
cluster_utils.AutoscalingCluster over the fake node provider)."""

import time

import pytest

import ray_tpu as rt
from ray_tpu.autoscaler import Autoscaler, AutoscalingCluster


def test_bin_packing_counts_nodes():
    a = Autoscaler.__new__(Autoscaler)
    a.node_type = {"CPU": 2.0}
    # 3 x 1-CPU shapes fit in 2 nodes; a 4-CPU shape can never fit
    assert a._nodes_needed([{"CPU": 1.0}] * 3) == 2
    assert a._nodes_needed([{"CPU": 4.0}]) == 0
    assert a._nodes_needed([]) == 0
    assert a._nodes_needed([{"CPU": 2.0}, {"CPU": 2.0}]) == 2


def test_scale_up_then_down():
    cluster = AutoscalingCluster(
        head_resources={"CPU": 1.0},
        worker_node_type={"CPU": 2.0},
        max_workers=2,
        idle_timeout_s=6.0)
    try:
        rt.init(address=cluster.address, _system_config={
            "infeasible_grace_s": 60.0,
        })

        @rt.remote(num_cpus=2)
        def heavy(i):
            time.sleep(1.0)
            return i

        # head node has 1 CPU: these shapes are infeasible until the
        # autoscaler reacts to the recorded demand
        t0 = time.monotonic()
        out = rt.get([heavy.remote(i) for i in range(4)], timeout=120)
        assert sorted(out) == [0, 1, 2, 3]
        assert len(rt.nodes()) >= 2, "no worker node was launched"

        # drain: nodes idle past the timeout must be terminated
        deadline = time.monotonic() + 45
        while time.monotonic() < deadline:
            alive = [n for n in rt.nodes() if n["Alive"]]
            if len(alive) == 1:
                break
            time.sleep(0.5)
        alive = [n for n in rt.nodes() if n["Alive"]]
        assert len(alive) == 1, f"idle nodes never scaled down: {alive}"
        rt.shutdown()
    finally:
        try:
            rt.shutdown()
        except Exception:
            pass
        cluster.shutdown()
