"""Shuffle-family operators + stateful actor-pool map (reference:
data/_internal/planner/exchange/ sort/aggregate task specs;
execution/operators/actor_pool_map_operator.py)."""

import numpy as np
import pytest

import ray_tpu as rt
from ray_tpu.data import ActorPoolStrategy, AggregateFn
from ray_tpu.data.read_api import from_items, range as range_ds


@pytest.fixture(scope="module")
def data_rt():
    rt.init(num_cpus=4, _system_config={
        "object_store_memory_bytes": 128 * 1024 * 1024,
        "worker_pool_prestart": 2,
    })
    yield rt
    rt.shutdown()


# -------------------------------------------------------------------- sort


def test_sort_scalars(data_rt):
    ds = from_items([5, 3, 8, 1, 9, 2, 7, 4, 6, 0], num_blocks=3)
    assert ds.sort().take_all() == list(range(10))
    assert ds.sort(descending=True).take_all() == list(range(9, -1, -1))


def test_sort_by_column(data_rt):
    rows = [{"k": (7 * i + 3) % 20, "v": i} for i in range(20)]
    ds = from_items(rows, num_blocks=4)
    out = ds.sort(key="k").take_all()
    ks = [r["k"] for r in out]
    assert ks == sorted(ks)
    assert len(out) == 20


def test_sort_with_key_fn(data_rt):
    ds = from_items(["bbb", "a", "cc", "dddd"], num_blocks=2)
    assert ds.sort(key=len).take_all() == ["a", "cc", "bbb", "dddd"]


# ----------------------------------------------------------------- groupby


def test_groupby_count_and_sum(data_rt):
    rows = [{"k": i % 3, "v": float(i)} for i in range(12)]
    ds = from_items(rows, num_blocks=4)
    counts = {r["k"]: r["count()"]
              for r in ds.groupby("k").count().take_all()}
    assert counts == {0: 4, 1: 4, 2: 4}
    sums = {r["k"]: r["sum(v)"]
            for r in ds.groupby("k").sum("v").take_all()}
    assert sums == {0: 0 + 3 + 6 + 9, 1: 1 + 4 + 7 + 10, 2: 2 + 5 + 8 + 11}


def test_groupby_multi_aggregate(data_rt):
    rows = [{"k": "a" if i < 5 else "b", "v": float(i)} for i in range(10)]
    ds = from_items(rows, num_blocks=3)
    out = {r["k"]: r for r in ds.groupby("k").aggregate(
        AggregateFn.mean("v"), AggregateFn.min("v"),
        AggregateFn.max("v")).take_all()}
    assert out["a"]["mean(v)"] == 2.0
    assert out["a"]["min(v)"] == 0.0 and out["a"]["max(v)"] == 4.0
    assert out["b"]["mean(v)"] == 7.0


def test_groupby_map_groups(data_rt):
    rows = [{"k": i % 2, "v": i} for i in range(8)]
    ds = from_items(rows, num_blocks=2)
    out = ds.groupby("k").map_groups(
        lambda rows: {"k": rows[0]["k"],
                      "vs": sorted(r["v"] for r in rows)}).take_all()
    by_k = {r["k"]: list(r["vs"]) for r in out}
    assert by_k == {0: [0, 2, 4, 6], 1: [1, 3, 5, 7]}


def test_dataset_level_aggregate(data_rt):
    ds = range_ds(100, num_blocks=5)  # rows are {"id": int}
    out = ds.aggregate(AggregateFn.sum("id"), AggregateFn.count())
    assert out["sum(id)"] == sum(range(100))
    assert out["count()"] == 100


# ------------------------------------------------------------- actor pools


def test_map_batches_actor_pool(data_rt):
    class AddModelBias:
        """Stateful UDF: 'loads a model' once per pool actor."""

        def __init__(self, bias):
            import os
            self.bias = bias
            self.pid = os.getpid()

        def __call__(self, batch):
            return {"x": batch["x"] + self.bias, "pid":
                    np.full(len(batch["x"]), self.pid)}

    rows = [{"x": float(i)} for i in range(40)]
    ds = from_items(rows, num_blocks=8).map_batches(
        AddModelBias, compute=ActorPoolStrategy(size=2),
        fn_constructor_args=(100.0,))
    out = ds.take_all()
    assert sorted(r["x"] for r in out) == [100.0 + i for i in range(40)]
    # the pool actually used distinct stateful actors
    pids = {int(r["pid"]) for r in out}
    assert 1 <= len(pids) <= 2


def test_actor_pool_then_transform(data_rt):
    class Doubler:
        def __call__(self, batch):
            return {"x": batch["x"] * 2}

    ds = (from_items([{"x": float(i)} for i in range(10)], num_blocks=2)
          .map_batches(Doubler, compute=ActorPoolStrategy(size=1))
          .map(lambda r: {"x": r["x"] + 1}))
    assert sorted(r["x"] for r in ds.take_all()) == \
        [2.0 * i + 1 for i in range(10)]
