"""Concurrency groups: control-lane methods must stay responsive while
every default-lane thread is blocked (reference:
core_worker/transport/concurrency_group_manager.h semantics)."""

import time

import pytest

import ray_tpu as rt


@pytest.fixture(scope="module")
def cluster_rt():
    rt.init(num_cpus=2, _system_config={
        "object_store_memory_bytes": 64 * 1024 * 1024,
    })
    yield rt
    rt.shutdown()


def test_control_group_bypasses_busy_lanes(cluster_rt):
    @rt.remote(max_concurrency=2, concurrency_groups={"control": 1})
    class Busy:
        @rt.method(concurrency_group="control")
        def ping(self):
            return "pong"

        def block(self, s):
            time.sleep(s)
            return "done"

    b = Busy.remote()
    assert rt.get(b.ping.remote(), timeout=60) == "pong"
    # saturate both default lanes, then some
    blockers = [b.block.remote(3.0) for _ in range(4)]
    time.sleep(0.3)
    t0 = time.monotonic()
    assert rt.get(b.ping.remote(), timeout=30) == "pong"
    dt = time.monotonic() - t0
    assert dt < 1.5, f"control method queued behind busy lanes: {dt:.2f}s"
    assert rt.get(blockers, timeout=60) == ["done"] * 4
