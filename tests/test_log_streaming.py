"""Worker stdout/stderr streaming to the submitting driver (reference:
python/ray/_private/log_monitor.py tailing -> GCS pubsub -> driver prints
with the (pid=...) prefix, worker.py:1970 — here a direct worker->owner
push attributed per task)."""

import sys
import time

import pytest

import ray_tpu as rt


@pytest.fixture(scope="module")
def log_rt():
    rt.init(num_cpus=2, _system_config={
        "object_store_memory_bytes": 64 * 1024 * 1024,
    })
    yield rt
    rt.shutdown()


def _wait_for(capsys, needle: str, timeout: float = 10.0) -> str:
    collected = ""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        captured = capsys.readouterr()
        collected += captured.out + captured.err
        if needle in collected:
            return collected
        time.sleep(0.2)
    raise AssertionError(f"{needle!r} never reached the driver; "
                         f"got: {collected[-2000:]!r}")


def test_task_print_reaches_driver(log_rt, capsys):
    @rt.remote
    def chatty():
        print("hello-from-worker-xyzzy")
        return 1

    assert rt.get(chatty.remote(), timeout=60) == 1
    out = _wait_for(capsys, "hello-from-worker-xyzzy")
    # attributed with the worker prefix, like the reference's (pid=...)
    line = next(l for l in out.splitlines()
                if "hello-from-worker-xyzzy" in l)
    assert "pid=" in line


def test_stderr_reaches_driver(log_rt, capsys):
    @rt.remote
    def warns():
        print("warning-grobble", file=sys.stderr)
        return 2

    assert rt.get(warns.remote(), timeout=60) == 2
    _wait_for(capsys, "warning-grobble")


def test_actor_method_print_reaches_driver(log_rt, capsys):
    @rt.remote
    class Talker:
        def speak(self):
            print("actor-says-quux")
            return "ok"

    t = Talker.remote()
    assert rt.get(t.speak.remote(), timeout=60) == "ok"
    _wait_for(capsys, "actor-says-quux")
