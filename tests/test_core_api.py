"""Core API semantics in local mode.

Covers what the reference's python/ray/tests/test_basic*.py cover for
local-mode: put/get/wait, tasks, multiple returns, nested refs, errors,
actors (state, ordering, named, kill), cancellation.
"""

import time

import pytest


def test_put_get(rtpu_local):
    rt = rtpu_local
    ref = rt.put({"a": [1, 2, 3]})
    assert rt.get(ref) == {"a": [1, 2, 3]}


def test_task_roundtrip(rtpu_local):
    rt = rtpu_local

    @rt.remote
    def add(x, y):
        return x + y

    assert rt.get(add.remote(2, 3)) == 5


def test_task_with_ref_args(rtpu_local):
    rt = rtpu_local

    @rt.remote
    def double(x):
        return 2 * x

    a = rt.put(21)
    assert rt.get(double.remote(a)) == 42
    # chained
    assert rt.get(double.remote(double.remote(a))) == 84


def test_multiple_returns(rtpu_local):
    rt = rtpu_local

    @rt.remote(num_returns=2)
    def divmod_task(a, b):
        return a // b, a % b

    q, r = divmod_task.remote(17, 5)
    assert rt.get(q) == 3
    assert rt.get(r) == 2


def test_task_error_propagates(rtpu_local):
    rt = rtpu_local

    @rt.remote
    def boom():
        raise ValueError("kapow")

    with pytest.raises(rt.exceptions.TaskError) as ei:
        rt.get(boom.remote())
    assert "kapow" in str(ei.value)


def test_error_propagates_through_dependency(rtpu_local):
    rt = rtpu_local

    @rt.remote
    def boom():
        raise RuntimeError("first failure")

    @rt.remote
    def identity(x):
        return x

    with pytest.raises(rt.exceptions.TaskError):
        rt.get(identity.remote(boom.remote()))


def test_wait(rtpu_local):
    rt = rtpu_local

    @rt.remote
    def fast():
        return 1

    @rt.remote
    def slow():
        time.sleep(5)
        return 2

    f, s = fast.remote(), slow.remote()
    ready, pending = rt.wait([f, s], num_returns=1, timeout=3)
    assert ready == [f]
    assert pending == [s]


def test_get_timeout(rtpu_local):
    rt = rtpu_local

    @rt.remote
    def sleeper():
        time.sleep(10)

    with pytest.raises(rt.exceptions.GetTimeoutError):
        rt.get(sleeper.remote(), timeout=0.2)


def test_actor_state_and_ordering(rtpu_local):
    rt = rtpu_local

    @rt.remote
    class Counter:
        def __init__(self, start=0):
            self.value = start

        def incr(self, by=1):
            self.value += by
            return self.value

        def get_value(self):
            return self.value

    c = Counter.remote(10)
    refs = [c.incr.remote() for _ in range(20)]
    assert rt.get(refs) == list(range(11, 31))
    assert rt.get(c.get_value.remote()) == 30


def test_actor_error_does_not_kill_actor(rtpu_local):
    rt = rtpu_local

    @rt.remote
    class A:
        def ok(self):
            return "ok"

        def fail(self):
            raise RuntimeError("method error")

    a = A.remote()
    with pytest.raises(rt.exceptions.TaskError):
        rt.get(a.fail.remote())
    assert rt.get(a.ok.remote()) == "ok"


def test_named_actor(rtpu_local):
    rt = rtpu_local

    @rt.remote
    class Store:
        def __init__(self):
            self.d = {}

        def set(self, k, v):
            self.d[k] = v

        def get(self, k):
            return self.d.get(k)

    Store.options(name="kv").remote()
    handle = rt.get_actor("kv")
    rt.get(handle.set.remote("x", 7))
    assert rt.get(handle.get.remote("x")) == 7


def test_kill_actor(rtpu_local):
    rt = rtpu_local

    @rt.remote
    class A:
        def ping(self):
            return "pong"

    a = A.remote()
    assert rt.get(a.ping.remote()) == "pong"
    rt.kill(a)
    time.sleep(0.1)
    with pytest.raises(rt.exceptions.ActorError):
        rt.get(a.ping.remote(), timeout=5)


def test_actor_handle_passing(rtpu_local):
    rt = rtpu_local

    @rt.remote
    class Counter:
        def __init__(self):
            self.n = 0

        def incr(self):
            self.n += 1
            return self.n

    @rt.remote
    def bump(counter):
        return rt.get(counter.incr.remote())

    c = Counter.remote()
    results = rt.get([bump.remote(c) for _ in range(5)])
    assert sorted(results) == [1, 2, 3, 4, 5]


def test_options_override(rtpu_local):
    rt = rtpu_local

    @rt.remote
    def f():
        return "x"

    ref = f.options(name="custom", num_returns=1).remote()
    assert rt.get(ref) == "x"


def test_runtime_context(rtpu_local):
    rt = rtpu_local
    ctx = rt.get_runtime_context()
    assert not ctx.job_id.is_nil()
    assert len(ctx.get()["worker_id"]) == 32


def test_nested_tasks(rtpu_local):
    rt = rtpu_local

    @rt.remote
    def inner(x):
        return x * 2

    @rt.remote
    def outer(x):
        return rt.get(inner.remote(x)) + 1

    assert rt.get(outer.remote(10)) == 21


def test_cluster_resources_local(rtpu_local):
    rt = rtpu_local
    assert rt.cluster_resources()["CPU"] == 4.0


def test_method_decorator_num_returns(rtpu_local):
    rt = rtpu_local

    @rt.remote
    class M:
        @rt.method(num_returns=2)
        def pair(self):
            return 1, 2

    m = M.remote()
    a, b = m.pair.remote()
    assert rt.get(a) == 1
    assert rt.get(b) == 2


def test_wait_caps_ready_at_num_returns(rtpu_local):
    rt = rtpu_local
    refs = [rt.put(i) for i in range(5)]
    ready, pending = rt.wait(refs, num_returns=2, timeout=5)
    assert len(ready) == 2
    assert len(pending) == 3
