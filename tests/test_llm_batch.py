"""Batch LLM inference over datasets (reference: ray.data.llm vLLM
engine stage — llm/_internal/batch/stages/vllm_engine_stage.py)."""

import pytest

import ray_tpu
from ray_tpu import data as rd
from ray_tpu.llm import batch_inference


@pytest.fixture(scope="module")
def rt():
    ray_tpu.init(num_cpus=4)
    yield
    ray_tpu.shutdown()


def test_batch_inference_text_prompts(rt):
    ds = rd.from_items([{"prompt": "hello", "id": 0},
                        {"prompt": "worldly", "id": 1},
                        {"prompt": "abc", "id": 2}])
    out = batch_inference(
        ds, model_config={"n_layers": 2}, max_new_tokens=4,
        engine_config={"page_size": 8, "total_pages": 64, "max_batch": 4,
                       "max_seq_len": 64},
        concurrency=1).take_all()
    assert len(out) == 3
    by_id = {r["id"]: r for r in out}
    for i in range(3):
        r = by_id[i]
        assert len(r["generated"]) == 4           # token ids
        assert isinstance(r["generated_text"], str)
        assert r["prompt"]                         # original row kept


def test_batch_inference_is_deterministic_per_prompt(rt):
    """The same prompt through the pool gives the same greedy tokens
    regardless of which rows share its block (engine invariance). With
    prefix caching on by default this also pins hit-vs-cold parity: the
    first "repeat me" in each engine prefills cold, the rest reuse its
    cached full page and chunk-prefill only the tail — the greedy
    stream must be identical either way."""
    rows = [{"prompt": "repeat me"} for _ in range(6)]
    out = batch_inference(
        rd.from_items(rows, num_blocks=3),
        model_config={"n_layers": 2}, max_new_tokens=5,
        engine_config={"page_size": 8, "total_pages": 64, "max_batch": 4,
                       "max_seq_len": 64},
        concurrency=2).take_all()
    gens = {tuple(r["generated"]) for r in out}
    assert len(gens) == 1, gens
