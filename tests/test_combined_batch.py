"""Combined batch frames: one request frame carrying N task payloads, one
reply frame carrying N (value, error) pairs (cluster_backend._push_batch →
worker_main.handle_push_task_batch → _BatchReplyCollector).

Protocol-level coverage on both transports plus end-to-end semantics the
suite's throughput tests don't pin down: per-task error isolation inside a
batch, ordering, and the malformed-reply guard."""

import threading

import pytest

import ray_tpu as rt
from ray_tpu.runtime.protocol import DEFERRED, RpcClient, RpcServer


@pytest.fixture
def echo_server():
    # a combined-method handler receives the WHOLE payload list and must
    # reply with one (value, error) pair per item — the worker's
    # handle_push_task_batch contract
    def handle_echo(payloads, ctx):
        out = []
        for p in payloads:
            if p == "boom":
                out.append((None, ValueError("boom payload")))
            else:
                out.append((("echo", p), None))
        return out

    def handle_bad_combined(payload, ctx):
        return "not-a-list"  # malformed combined reply

    srv = RpcServer({"echo": handle_echo,
                     "bad": handle_bad_combined}, name="combined-test")
    yield srv
    srv.stop()


def test_call_combined_cb_fans_out(echo_server):
    client = RpcClient(echo_server.address)
    got = {}
    done = threading.Event()

    def cb(i, v, e):
        got[i] = (v, e)
        if len(got) == 4:
            done.set()

    client.call_combined_cb("echo", ["a", "b", "boom", "c"], cb)
    assert done.wait(10), f"only {len(got)} callbacks fired"
    assert got[0] == (("echo", "a"), None)
    assert got[3] == (("echo", "c"), None)
    # per-item error isolation: item 2 failed, neighbours unaffected
    assert got[2][0] is None and isinstance(got[2][1], ValueError)
    client.close()


def test_combined_malformed_reply_surfaces_error(echo_server):
    client = RpcClient(echo_server.address)
    got = {}
    done = threading.Event()

    def cb(i, v, e):
        got[i] = (v, e)
        if len(got) == 2:
            done.set()

    client.call_combined_cb("bad", ["x", "y"], cb)
    assert done.wait(10)
    for i in (0, 1):
        v, e = got[i]
        assert v is None and e is not None, \
            f"malformed combined reply not surfaced: {got[i]}"
    client.close()


@pytest.fixture
def eager_server():
    """Handler that replies per slot via ctx.slot_ids/ctx.reply_to: slot 0
    immediately, slot 1 only after `release` fires — then the done
    marker. Models a worker flushing each task as it finishes."""
    from ray_tpu.runtime.protocol import _COMBINED_DONE
    state = {"release": threading.Event(), "slot_ids": None}

    def handle_eager(payloads, ctx):
        state["slot_ids"] = ctx.slot_ids
        if ctx.slot_ids is None:  # old-format client: single reply
            return [((p, "done"), None) for p in payloads]
        ctx.reply_to(ctx.slot_ids[0], (payloads[0], "done"), None)

        def later():
            state["release"].wait(10)
            ctx.reply_to(ctx.slot_ids[1], (payloads[1], "done"), None)
            ctx.reply(_COMBINED_DONE)
        threading.Thread(target=later, daemon=True).start()
        return DEFERRED

    srv = RpcServer({"eager": handle_eager}, name="eager-test")
    yield srv, state
    state["release"].set()
    srv.stop()


def test_combined_replies_flush_eagerly(eager_server):
    """A completed slot's callback fires BEFORE the rest of the batch
    finishes — the fix for nested-get deadlocks where task A waited on a
    ref whose producing task B sat in the same withheld batch reply."""
    srv, state = eager_server
    client = RpcClient(srv.address)
    got = {}
    first = threading.Event()
    done = threading.Event()

    def cb(i, v, e):
        got[i] = (v, e)
        if i == 0:
            first.set()
        if len(got) == 2:
            done.set()

    client.call_combined_cb("eager", ["a", "b"], cb)
    # slot 0 must arrive while slot 1 is still held open server-side
    assert first.wait(10), "eager slot reply never fired"
    assert got[0] == (("a", "done"), None)
    assert not done.is_set()
    state["release"].set()
    assert done.wait(10), "batch never completed after release"
    assert got[1] == (("b", "done"), None)
    assert state["slot_ids"] is not None and len(state["slot_ids"]) == 2
    client.close()


def test_batch_error_isolation_end_to_end():
    """One failing task inside a burst must not poison its batchmates."""
    rt.init(num_cpus=2, _system_config={
        "object_store_memory_bytes": 64 * 1024 * 1024})
    try:
        @rt.remote
        def maybe_fail(i):
            if i == 7:
                raise RuntimeError(f"task {i} fails")
            return i * 2

        refs = [maybe_fail.remote(i) for i in range(20)]
        ok, bad = 0, 0
        for i, r in enumerate(refs):
            try:
                v = rt.get(r, timeout=60)
                assert v == i * 2
                ok += 1
            except Exception:
                assert i == 7
                bad += 1
        assert ok == 19 and bad == 1
    finally:
        rt.shutdown()
