"""Chunked, striped cross-node object transfer (reference:
object_manager push/pull chunking — push_manager.h:30 chunk windowing,
pull_manager.h:53 admission; OwnershipBasedObjectDirectory location set).

The chunk size is configured far below the object sizes here, so every
transfer in this file exercises the pipelined read_chunk path rather than
a matching-size single read_object frame.
"""

import numpy as np
import pytest

import ray_tpu as rt
from ray_tpu.cluster_utils import Cluster

CHUNK = 256 * 1024


@pytest.fixture()
def chunked_cluster():
    cluster = Cluster()
    cluster.add_node(num_cpus=2, resources={"nodeA": 1})
    cluster.add_node(num_cpus=2, resources={"nodeB": 1})
    cluster.add_node(num_cpus=2, resources={"nodeC": 1})
    rt.init(address=cluster.address, _system_config={
        "object_transfer_chunk_bytes": CHUNK,
        "health_check_period_ms": 200,
        "health_check_timeout_ms": 1500,
        "object_store_memory_bytes": 128 * 1024 * 1024,
    })
    yield cluster
    rt.shutdown()
    cluster.shutdown()


def test_multichunk_transfer_integrity(chunked_cluster):
    """An object spanning many chunks arrives bit-exact (order-independent
    chunk assembly) on another node."""
    n = 1_000_000  # 8 MB -> 32 chunks of 256 KiB

    @rt.remote(resources={"nodeB": 0.1})
    def make():
        return np.arange(n, dtype=np.float64)

    out = rt.get(make.remote(), timeout=120)
    assert out.shape == (n,)
    # spot-check across chunk boundaries, not just the ends
    idx = np.arange(0, n, 31_337)
    np.testing.assert_array_equal(out[idx], idx.astype(np.float64))


def test_broadcast_to_many_nodes(chunked_cluster):
    """One producer, consumers on every other node: all see identical
    bytes, and secondary copies registered with the owner let later pulls
    stripe across multiple holders."""

    @rt.remote(resources={"nodeA": 0.1})
    def produce():
        rng = np.random.default_rng(7)
        return rng.integers(0, 255, size=750_000, dtype=np.int64)  # ~6 MB

    @rt.remote
    def digest(x):
        return int(x.sum()), x.shape[0]

    ref = produce.remote()
    expected = rt.get(digest.options(resources={"nodeA": 0.1}).remote(ref),
                      timeout=120)
    outs = rt.get(
        [digest.options(resources={node: 0.1}).remote(ref)
         for node in ("nodeB", "nodeC", "nodeB", "nodeC")], timeout=180)
    assert all(o == expected for o in outs)


def test_spilled_object_chunked_read(chunked_cluster):
    """Chunk reads fall back to the holder's spill files for
    disk-overflowed objects."""

    @rt.remote(resources={"nodeC": 0.1})
    def make_many():
        # enough 8 MB objects to overflow a 128 MB arena on node C
        return [rt.put(np.full(1_000_000, i, np.float64))
                for i in range(20)]

    refs = rt.get(make_many.remote(), timeout=180)
    # read them from the driver's node: early ones were spilled on node C
    for i in [0, 1, 10, 19]:
        arr = rt.get(refs[i], timeout=120)
        assert float(arr[0]) == float(i) and arr.shape == (1_000_000,)
