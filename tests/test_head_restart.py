"""Head (GCS) restart recovery beyond KV.

Mirrors the reference's GCS fault-tolerance contract (reference:
src/ray/gcs/gcs_server/gcs_init_data.h table reload on boot,
gcs_actor_manager.h:324 actor re-registration, raylet reconnect): after a
hard head kill + restart on the same address with the same persistence
path, node daemons re-register themselves (carrying live actors and
in-use resources), named actors resolve and keep their in-memory state,
and fresh task submission works.
"""

import os
import signal
import time

import pytest

import ray_tpu
from ray_tpu.runtime.cluster_backend import start_head, start_node
from ray_tpu.runtime.protocol import RpcClient, RpcError


def _wait_alive_nodes(addr, n, timeout=30.0):
    c = RpcClient(addr, name="probe")
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            if sum(x["alive"] for x in c.call("list_nodes", timeout=2)) >= n:
                c.close()
                return
        except RpcError:
            pass
        time.sleep(0.1)
    c.close()
    raise AssertionError(f"{n} nodes never registered at {addr}")


def test_actor_and_tasks_survive_head_restart(tmp_path):
    persist = str(tmp_path / "gcs.pkl")
    session = "headrestart"
    head_proc, addr = start_head(session, persist_path=persist)
    port = int(addr.rsplit(":", 1)[1])
    node_proc = start_node(addr, session, resources={"CPU": 2.0})
    head_proc2 = None
    try:
        _wait_alive_nodes(addr, 1)
        ray_tpu.init(address=addr)

        @ray_tpu.remote
        class Counter:
            def __init__(self):
                self.n = 0

            def incr(self):
                self.n += 1
                return self.n

        a = Counter.options(name="survivor", lifetime="detached").remote()
        assert ray_tpu.get(a.incr.remote(), timeout=60) == 1

        # hard-kill the head mid-workload
        os.kill(head_proc.pid, signal.SIGKILL)
        head_proc.wait(timeout=10)

        # actor RPC is direct worker-to-worker: it must keep serving even
        # while the control plane is down
        assert ray_tpu.get(a.incr.remote(), timeout=30) == 2

        # restart the head on the SAME address with the same snapshot
        head_proc2, addr2 = start_head(session, port=port,
                                       persist_path=persist)
        assert addr2 == addr
        # the node daemon notices and re-registers (with its live actor)
        _wait_alive_nodes(addr, 1)

        # named-actor lookup through the NEW head resolves to the SAME
        # still-running instance (state preserved: counter continues)
        deadline = time.monotonic() + 30
        while True:
            try:
                b = ray_tpu.get_actor("survivor")
                got = ray_tpu.get(b.incr.remote(), timeout=10)
                break
            except Exception:
                if time.monotonic() > deadline:
                    raise
                time.sleep(0.2)
        assert got == 3

        # fresh task submission goes through the recovered lease path
        @ray_tpu.remote
        def seven():
            return 7

        assert ray_tpu.get(seven.remote(), timeout=60) == 7
    finally:
        try:
            ray_tpu.shutdown()
        except Exception:
            pass
        for proc in (node_proc, head_proc2):
            if proc is None:
                continue
            try:
                proc.terminate()
                proc.wait(timeout=5)
            except Exception:
                try:
                    proc.kill()
                except Exception:
                    pass


def test_recovered_lease_release_frees_resources(tmp_path):
    """A lease granted by the old head is released through the new head
    (keyed by worker when the lease id is unknown) so resources do not
    leak after recovery."""
    persist = str(tmp_path / "gcs2.pkl")
    session = "headrestart2"
    head_proc, addr = start_head(session, persist_path=persist)
    port = int(addr.rsplit(":", 1)[1])
    node_proc = start_node(addr, session, resources={"CPU": 1.0})
    head_proc2 = None
    try:
        _wait_alive_nodes(addr, 1)
        ray_tpu.init(address=addr)

        @ray_tpu.remote
        def hold(t):
            time.sleep(t)
            return os.getpid()

        # occupy the single CPU slot through the old head's lease
        ref = hold.remote(4.0)
        time.sleep(1.0)  # ensure the lease is held and the task is running
        os.kill(head_proc.pid, signal.SIGKILL)
        head_proc.wait(timeout=10)
        head_proc2, _ = start_head(session, port=port, persist_path=persist)
        _wait_alive_nodes(addr, 1)
        # in-flight task completes across the restart
        assert isinstance(ray_tpu.get(ref, timeout=60), int)
        # after release, the CPU slot must be usable again via the new head
        assert ray_tpu.get(hold.remote(0.01), timeout=60) > 0
    finally:
        try:
            ray_tpu.shutdown()
        except Exception:
            pass
        for proc in (node_proc, head_proc2):
            if proc is None:
                continue
            try:
                proc.terminate()
                proc.wait(timeout=5)
            except Exception:
                try:
                    proc.kill()
                except Exception:
                    pass
