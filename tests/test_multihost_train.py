"""Multi-host JaxTrainer: 2 worker processes x 4 virtual CPU devices form
ONE 8-device global mesh via jax.distributed, train tiny-Llama FSDP, and
match the single-process loss (VERDICT round-1 item 5 done-criterion;
reference analog: torch process-group rendezvous, train/torch/config.py:66).
"""

import pytest

import ray_tpu as rt
from ray_tpu import train
from ray_tpu.parallel.mesh import MeshSpec


def _make_loop():
    """Defined inside a function so cloudpickle ships it BY VALUE (worker
    processes cannot import the test module)."""
    def loop(cfg):
        import jax
        import jax.numpy as jnp
        import numpy as np
        import optax

        from ray_tpu.models import llama
        from ray_tpu.train.train_step import (make_train_step, shard_batch,
                                              shard_params)

        ctx = train.get_context()
        assert jax.process_count() == cfg["expect_processes"]
        assert len(jax.devices()) == 8, jax.devices()
        mesh = ctx.global_mesh()
        assert mesh.shape["fsdp"] == 8

        mcfg = llama.LlamaConfig.tiny(n_layers=2)
        params = llama.init_params(mcfg, jax.random.PRNGKey(11))
        with mesh:
            params = shard_params(params, mesh, llama.param_specs(mcfg))
            opt = optax.sgd(1e-2)
            init_fn, step_fn = make_train_step(
                lambda p, b: llama.loss_fn(p, b, mcfg), opt)
            opt_state = init_fn(params)
            rng = np.random.default_rng(11)
            for _ in range(3):
                batch = rng.integers(
                    0, mcfg.vocab_size, (8, 32)).astype(np.int32)
                batch = shard_batch(jnp.asarray(batch), mesh)
                params, opt_state, metrics = step_fn(
                    params, opt_state, batch)
            train.report({"loss": float(metrics["loss"])})
    return loop


@pytest.fixture(scope="module")
def cluster_rt():
    rt.init(num_cpus=4, _system_config={
        "object_store_memory_bytes": 128 * 1024 * 1024,
    })
    yield rt
    rt.shutdown()


def _fit(num_workers, local_devices, name):
    trainer = train.JaxTrainer(
        _make_loop(),
        train_loop_config={"expect_processes": num_workers},
        scaling_config=train.ScalingConfig(
            num_workers=num_workers,
            mesh=MeshSpec(fsdp=-1),
            jax_distributed=True,
            jax_platform="cpu",
            local_device_count=local_devices),
        run_config=train.RunConfig(name=name))
    return trainer.fit()


def test_two_process_global_mesh_matches_single(cluster_rt, tmp_path):
    multi = _fit(2, 4, "mh2")
    single = _fit(1, 8, "mh1")
    assert multi.metrics["loss"] == pytest.approx(
        single.metrics["loss"], rel=2e-4), \
        (multi.metrics, single.metrics)
