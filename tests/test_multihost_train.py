"""Multi-host JaxTrainer: 2 worker processes x 4 virtual CPU devices form
ONE 8-device global mesh via jax.distributed, train tiny-Llama FSDP, and
match the single-process loss (VERDICT round-1 item 5 done-criterion;
reference analog: torch process-group rendezvous, train/torch/config.py:66).
"""

import time

import pytest

import ray_tpu as rt
from ray_tpu import train
from ray_tpu.parallel.mesh import MeshSpec


def _make_loop():
    """Defined inside a function so cloudpickle ships it BY VALUE (worker
    processes cannot import the test module)."""
    def loop(cfg):
        import jax
        import jax.numpy as jnp
        import numpy as np
        import optax

        from ray_tpu.models import llama
        from ray_tpu.train.train_step import (make_train_step, shard_batch,
                                              shard_params)

        ctx = train.get_context()
        assert jax.process_count() == cfg["expect_processes"]
        assert len(jax.devices()) == 8, jax.devices()
        mesh = ctx.global_mesh()
        assert mesh.shape["fsdp"] == 8

        mcfg = llama.LlamaConfig.tiny(n_layers=2)
        params = llama.init_params(mcfg, jax.random.PRNGKey(11))
        with mesh:
            params = shard_params(params, mesh, llama.param_specs(mcfg))
            opt = optax.sgd(1e-2)
            init_fn, step_fn = make_train_step(
                lambda p, b: llama.loss_fn(p, b, mcfg), opt)
            opt_state = init_fn(params)
            rng = np.random.default_rng(11)
            for _ in range(3):
                batch = rng.integers(
                    0, mcfg.vocab_size, (8, 32)).astype(np.int32)
                batch = shard_batch(jnp.asarray(batch), mesh)
                params, opt_state, metrics = step_fn(
                    params, opt_state, batch)
            train.report({"loss": float(metrics["loss"])})
    return loop


@pytest.fixture(scope="module")
def cluster_rt():
    rt.init(num_cpus=4, _system_config={
        "object_store_memory_bytes": 128 * 1024 * 1024,
    })
    yield rt
    rt.shutdown()


def _fit(num_workers, local_devices, name):
    trainer = train.JaxTrainer(
        _make_loop(),
        train_loop_config={"expect_processes": num_workers},
        scaling_config=train.ScalingConfig(
            num_workers=num_workers,
            mesh=MeshSpec(fsdp=-1),
            jax_distributed=True,
            jax_platform="cpu",
            local_device_count=local_devices),
        run_config=train.RunConfig(name=name))
    return trainer.fit()


def test_two_process_global_mesh_matches_single(cluster_rt, tmp_path):
    multi = _fit(2, 4, "mh2")
    single = _fit(1, 8, "mh1")
    assert multi.metrics["loss"] == pytest.approx(
        single.metrics["loss"], rel=2e-4), \
        (multi.metrics, single.metrics)


# ---------------------------------------------------------------- elastic

def _make_elastic_loop():
    """Worker loop for the elastic test: fixed batch (loss strictly
    decreases), checkpoint every step, rank 1 kills itself once."""
    def loop(cfg):
        import os

        import jax
        import jax.numpy as jnp
        import numpy as np
        import optax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ray_tpu.models import llama
        from ray_tpu.train.train_step import make_train_step, shard_batch

        ctx = train.get_context()
        mesh = ctx.global_mesh()
        n_dp = mesh.shape["dp"]

        # dp is THE elastic axis: params replicate (re-shard onto any world
        # size), the global batch is one fixed row tiled to dp — so the
        # mean loss is directly comparable across world sizes and strictly
        # decreasing under SGD (continuity check below).
        mcfg = llama.LlamaConfig.tiny(n_layers=2)
        params = llama.init_params(mcfg, jax.random.PRNGKey(7))
        opt = optax.sgd(5e-2)
        init_fn, step_fn = make_train_step(
            lambda p, b: llama.loss_fn(p, b, mcfg), opt)
        opt_state = init_fn(params)
        restored = ctx.get_checkpoint() is not None
        if restored:
            # restore re-shards host-numpy leaves onto the NEW (smaller)
            # mesh — the elastic re-mesh path under test
            state = ctx.get_checkpoint().load(
                target={"params": params, "opt": opt_state})
            params, opt_state = state["params"], state["opt"]
        with mesh:
            replicated = jax.tree.map(
                lambda x: jax.device_put(x, NamedSharding(mesh, P())),
                params)
            params = replicated
            opt_state = jax.tree.map(
                lambda x: jax.device_put(x, NamedSharding(mesh, P())),
                opt_state)
            rng = np.random.default_rng(3)
            row = rng.integers(0, mcfg.vocab_size, (1, 32)).astype(np.int32)
            fixed = np.tile(row, (n_dp, 1))
            while ctx.step < cfg["total_steps"]:
                if (ctx.get_rank() == 1 and ctx.step == cfg["kill_at"]
                        and not os.path.exists(cfg["marker"])):
                    open(cfg["marker"], "w").close()
                    os._exit(1)
                batch = shard_batch(jnp.asarray(fixed), mesh, spec=P("dp"))
                params, opt_state, metrics = step_fn(
                    params, opt_state, batch)
                train.report(
                    {"loss": float(metrics["loss"]),
                     "world_size": ctx.get_world_size(),
                     "n_devices": len(jax.devices()),
                     "restored": restored},
                    checkpoint_tree={"params": params, "opt": opt_state})
    return loop


def test_elastic_shrink_on_worker_loss(cluster_rt, tmp_path):
    """Kill 1 of 4 workers mid-run: the ScalingPolicy restarts the group
    at 3 workers, the mesh re-resolves over 6 devices, training restores
    from the last checkpoint and the loss keeps decreasing (VERDICT #2
    done-criterion; reference: train/v2 scaling_policy.py:29)."""
    marker = str(tmp_path / "killed-once")
    kill_at = 3
    # capacity-driven initial sizing is part of the policy under test:
    # wait until the previous tests' actors have released their CPUs so
    # the run deterministically starts at the full 4 workers
    deadline = time.monotonic() + 30
    while rt.available_resources().get("CPU", 0) < 4 and \
            time.monotonic() < deadline:
        time.sleep(0.2)
    trainer = train.JaxTrainer(
        _make_elastic_loop(),
        train_loop_config={"total_steps": 6, "kill_at": kill_at,
                           "marker": marker},
        scaling_config=train.ScalingConfig(
            num_workers=4,
            min_workers=2,
            # aggressive poll ON PURPOSE: the killed worker's freed CPU
            # reads as capacity gain immediately, and only the
            # grow_cooldown_s hysteresis (VERDICT r4 #8) keeps the
            # shrunken group from bouncing straight back to 4 — this
            # test now also covers kill+immediate-capacity-return
            # restarting AT MOST once (world_size stays 3 to the end)
            grow_poll_s=0.5,
            grow_cooldown_s=120.0,
            mesh=MeshSpec(dp=-1),
            jax_distributed=True,
            jax_platform="cpu",
            local_device_count=2),
        run_config=train.RunConfig(
            name="elastic1",
            storage_path=str(tmp_path),  # fresh per invocation: a stale
            # results dir would restore past total_steps and no-op the run
            failure_config=train.FailureConfig(max_failures=2)))
    result = trainer.fit()
    assert result.error is None, result.error
    history = result.metrics_history
    # the surviving run resumed at kill_at+1 on a 3-worker, 6-device mesh
    assert history[0]["_step"] == kill_at + 1, history[0]
    assert history[0]["restored"] is True
    assert result.metrics["world_size"] == 3
    assert result.metrics["n_devices"] == 6
    assert history[-1]["_step"] == 6
    # loss continuity: fixed batch + SGD decreases monotonically, so the
    # restored step must be BELOW the loss recorded at the kill-step
    # checkpoint (a re-initialized model would jump back to ~log(vocab))
    from ray_tpu.train.checkpoint import CheckpointManager
    killed_ckpt_metrics = __import__("json").load(open(
        CheckpointManager(result.path).dir_for(kill_at) + "/metrics.json"))
    assert history[0]["loss"] < killed_ckpt_metrics["loss"], \
        (history[0], killed_ckpt_metrics)


def test_elastic_requires_fill_axis(cluster_rt):
    trainer = train.JaxTrainer(
        _make_elastic_loop(),
        train_loop_config={},
        scaling_config=train.ScalingConfig(
            num_workers=2, min_workers=1, mesh=MeshSpec(fsdp=2)),
        run_config=train.RunConfig(name="elastic-bad"))
    with pytest.raises(ValueError, match="fill"):
        trainer.fit()


def test_elastic_policy_sizing():
    from ray_tpu.train.scaling_policy import ElasticScalingPolicy
    pol = ElasticScalingPolicy(2, 8, {"CPU": 2.0})
    assert pol.initial_size(lambda: {"CPU": 16.0}) == 8
    assert pol.initial_size(lambda: {"CPU": 9.0}) == 4
    assert pol.initial_size(lambda: {"CPU": 1.0}) == 2   # floor
    assert pol.after_failure(5, None) == 4
    assert pol.after_failure(2, None) == 2               # never below min


def test_elastic_grow_on_capacity_gain(cluster_rt, tmp_path):
    """Start capacity-constrained at 2 workers; free capacity mid-run and
    the grow monitor interrupts + restarts the group at 4, restored from
    the latest checkpoint (VERDICT #2 'on capacity gain, N+k')."""
    started_flag = str(tmp_path / "started")

    @rt.remote(num_cpus=2)
    class Hog:
        def ping(self):
            return True

    hog = Hog.remote()
    rt.get(hog.ping.remote())  # 2 of 4 CPUs held -> initial fit = 2
    # wait until the head's accounting reflects the hog, or initial_size
    # would optimistically start at 4 with two actors pending
    deadline = time.monotonic() + 30
    while rt.available_resources().get("CPU", 4) > 2 and \
            time.monotonic() < deadline:
        time.sleep(0.2)
    assert rt.available_resources().get("CPU", 0) <= 2

    def loop(cfg):
        import os
        import time as _t

        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        ctx = train.get_context()
        mesh = ctx.global_mesh()
        n = mesh.shape["dp"]
        arr = jax.device_put(jnp.arange(float(n)), NamedSharding(mesh, P("dp")))
        while ctx.step < cfg["steps"]:
            if ctx.get_rank() == 0 and ctx.step >= 1:
                open(cfg["started_flag"], "w").close()
            _t.sleep(0.25)
            # sharded tree -> checkpoint gather is a collective (lockstep)
            train.report({"world_size": ctx.get_world_size()},
                         checkpoint_tree={"x": arr, "step": ctx.step})

    trainer = train.JaxTrainer(
        loop,
        train_loop_config={"steps": 40, "started_flag": started_flag},
        scaling_config=train.ScalingConfig(
            num_workers=4,
            min_workers=1,
            grow_poll_s=0.5,
            mesh=MeshSpec(dp=-1),
            jax_distributed=True,
            jax_platform="cpu",
            local_device_count=2),
        run_config=train.RunConfig(
            name="elastic-grow", storage_path=str(tmp_path),
            failure_config=train.FailureConfig(max_failures=1)))

    # free the hog's 2 CPUs once the constrained group is actually training
    def _free_hog():
        deadline = time.monotonic() + 120
        import os
        while not os.path.exists(started_flag) and \
                time.monotonic() < deadline:
            time.sleep(0.2)
        rt.kill(hog)

    import threading
    threading.Thread(target=_free_hog, daemon=True).start()
    result = trainer.fit()
    assert result.error is None, result.error
    assert result.metrics["world_size"] == 4, result.metrics
    # restored continuation, not a from-scratch restart
    assert result.metrics_history[0]["_step"] > 1, result.metrics_history[0]
    assert result.metrics_history[-1]["_step"] == 40


def test_two_slice_hybrid_mesh_across_processes(cluster_rt):
    """2 worker processes x 4 devices = 2 'slices': dp spans slices (DCN)
    while fsdp stays inside each process's devices (ICI) — the multi-slice
    hybrid mesh trained through the real multi-process path
    (MeshSpec.dcn_dp; slice grouping falls out of process_index)."""
    def loop(cfg):
        import jax
        import jax.numpy as jnp
        import numpy as np
        import optax

        from ray_tpu.models import llama
        from ray_tpu.train.train_step import (make_train_step, shard_batch,
                                              shard_params)

        ctx = train.get_context()
        mesh = ctx.global_mesh()
        assert dict(mesh.shape) == {"pp": 1, "dp": 2, "fsdp": 4,
                                    "sp": 1, "tp": 1}, mesh.shape
        # slice locality: each dp block's devices live on ONE process
        for b in range(2):
            procs = {d.process_index for d in mesh.devices[:, b].flatten()}
            assert len(procs) == 1, (b, procs)

        mcfg = llama.LlamaConfig.tiny(n_layers=2)
        params = llama.init_params(mcfg, jax.random.PRNGKey(11))
        with mesh:
            params = shard_params(params, mesh, llama.param_specs(mcfg))
            init_fn, step_fn = make_train_step(
                lambda p, b: llama.loss_fn(p, b, mcfg), optax.sgd(1e-2))
            opt_state = init_fn(params)
            rng = np.random.default_rng(11)
            batch = rng.integers(0, mcfg.vocab_size, (8, 32)).astype(np.int32)
            batch = shard_batch(jnp.asarray(batch), mesh)
            params, opt_state, metrics = step_fn(params, opt_state, batch)
            loss = float(metrics["loss"])
            assert np.isfinite(loss)
            train.report({"loss": loss})

    result = train.JaxTrainer(
        loop,
        scaling_config=train.ScalingConfig(
            num_workers=2,
            mesh=MeshSpec(dcn_dp=2, fsdp=-1),
            jax_distributed=True,
            jax_platform="cpu",
            local_device_count=4),
        run_config=train.RunConfig(name="hybrid2")).fit()
    assert result.error is None, result.error
