"""Serve streaming responses end-to-end (reference: serve streaming
DeploymentResponseGenerator; proxy SSE; the OpenAI /v1/completions
contract from llm/_internal/serve/configs/openai_api_models.py)."""

import json
import time
import urllib.request

import pytest

import ray_tpu as rt
from ray_tpu import serve


@pytest.fixture(scope="module")
def stream_rt():
    rt.init(num_cpus=4, _system_config={
        "object_store_memory_bytes": 128 * 1024 * 1024,
        "worker_pool_prestart": 2,
    })
    yield rt
    serve.shutdown()
    rt.shutdown()


def _sse_frames(resp):
    """Parse data: frames off a streaming HTTP response as they arrive."""
    buf = b""
    while True:
        chunk = resp.read1(65536)
        if not chunk:
            return
        buf += chunk
        while b"\n\n" in buf:
            frame, buf = buf.split(b"\n\n", 1)
            for line in frame.splitlines():
                if line.startswith(b"data: "):
                    yield line[len(b"data: "):].decode()


def test_handle_streaming(stream_rt):
    @serve.deployment
    class Ticker:
        def ticks(self, req):
            n = req["n"]
            for i in range(n):
                yield {"tick": i, "t": time.time()}
                time.sleep(0.2)

    h = serve.run(Ticker.bind())
    t_consume = []
    items = []
    for item in h.ticks.options(stream=True).remote({"n": 4}):
        t_consume.append(time.time())
        items.append(item)
    assert [i["tick"] for i in items] == [0, 1, 2, 3]
    # incremental: the first item was consumed well before the last was
    # produced (producer sleeps 0.2s between yields)
    assert t_consume[0] < items[-1]["t"], \
        "stream was buffered, not incremental"


def test_http_sse_streaming(stream_rt):
    @serve.deployment
    class Counter:
        def __call__(self, req):
            for i in range(int(req["n"])):
                yield {"i": i}

    serve.run(Counter.bind())
    port = serve.start_http_proxy()
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/Counter",
        data=json.dumps({"n": 3, "stream": True}).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=120) as resp:
        assert resp.headers["Content-Type"].startswith("text/event-stream")
        frames = list(_sse_frames(resp))
    assert frames[-1] == "[DONE]"
    data = [json.loads(f) for f in frames[:-1]]
    assert [d["i"] for d in data] == [0, 1, 2]


def test_openai_completions_http(stream_rt):
    from ray_tpu.llm.serve_llm import LLMServer

    llm_app = serve.deployment(max_ongoing_requests=8, name="tinyllm")(
        LLMServer)
    serve.run(llm_app.bind(engine_config={"max_batch": 2,
                                          "total_pages": 64,
                                          "max_seq_len": 256,
                                          "decode_chunk": 4}))
    port = serve.start_http_proxy()

    # non-streaming: OpenAI completion shape
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/v1/completions",
        data=json.dumps({"model": "tinyllm", "prompt": "hello tpu",
                         "max_tokens": 8, "timeout_s": 240}).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=300) as resp:
        body = json.loads(resp.read())  # OpenAI shape: NOT wrapped
    assert body["object"] == "text_completion"
    assert body["choices"][0]["finish_reason"] == "length"
    assert len(body["choices"][0]["token_ids"]) == 8
    assert body["usage"]["prompt_tokens"] == len("hello tpu")

    # streaming: SSE chunks with text deltas, then [DONE]
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/v1/completions",
        data=json.dumps({"model": "tinyllm", "prompt": "stream me",
                         "max_tokens": 8, "stream": True}).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=300) as resp:
        frames = list(_sse_frames(resp))
    assert frames[-1] == "[DONE]"
    chunks = [json.loads(f) for f in frames[:-1]]
    assert all(c["object"] == "text_completion.chunk" for c in chunks)
    total = sum(len(c["choices"][0]["token_ids"]) for c in chunks)
    assert total == 8  # all deltas add up to max_tokens


def test_openai_chat_completions_http(stream_rt):
    """/v1/chat/completions with role templating + usage accounting,
    non-streaming and SSE (VERDICT r4 #7; reference:
    llm/_internal/serve/configs/openai_api_models.py
    ChatCompletionRequest). Reuses the deployment from the completions
    test via the module fixture ordering-independent re-run."""
    from ray_tpu.llm.serve_llm import LLMServer, apply_chat_template

    llm_app = serve.deployment(max_ongoing_requests=8, name="chatllm")(
        LLMServer)
    serve.run(llm_app.bind(engine_config={"max_batch": 2,
                                          "total_pages": 64,
                                          "max_seq_len": 256,
                                          "decode_chunk": 4}))
    port = serve.start_http_proxy()
    messages = [{"role": "system", "content": "you are tiny"},
                {"role": "user", "content": "hello"}]
    n_prompt = len(apply_chat_template(messages).encode())

    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/v1/chat/completions",
        data=json.dumps({"model": "chatllm", "messages": messages,
                         "max_tokens": 8, "timeout_s": 240}).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=300) as resp:
        body = json.loads(resp.read())
    assert body["object"] == "chat.completion"
    msg = body["choices"][0]["message"]
    assert msg["role"] == "assistant" and isinstance(msg["content"], str)
    assert body["choices"][0]["finish_reason"] == "length"
    assert body["usage"]["prompt_tokens"] == n_prompt
    assert body["usage"]["completion_tokens"] == 8
    assert body["usage"]["total_tokens"] == n_prompt + 8

    # streaming: role delta first, content deltas, terminal chunk with
    # finish_reason + usage, then [DONE]
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/v1/chat/completions",
        data=json.dumps({"model": "chatllm", "messages": messages,
                         "max_tokens": 8, "stream": True}).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=300) as resp:
        frames = list(_sse_frames(resp))
    assert frames[-1] == "[DONE]"
    chunks = [json.loads(f) for f in frames[:-1]]
    assert all(c["object"] == "chat.completion.chunk" for c in chunks)
    assert chunks[0]["choices"][0]["delta"].get("role") == "assistant"
    final = chunks[-1]
    assert final["choices"][0]["finish_reason"] == "length"
    assert final["usage"]["completion_tokens"] == 8
    content = "".join(c["choices"][0]["delta"].get("content", "")
                      for c in chunks)
    assert len(content) > 0
