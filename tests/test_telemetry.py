"""Cluster hardware telemetry: sampler probes over a faked /proc tree,
head-side time-series rings, Prometheus exposition round-trip, and the
/metrics + /api/timeseries + `top` surfaces against a live cluster."""

import json
import time
import urllib.request

import pytest

import ray_tpu as rt
from ray_tpu.runtime.hw_sampler import HardwareSampler
from ray_tpu.util import metrics as metrics_mod
from ray_tpu.util import prometheus
from ray_tpu.util.timeseries import TimeSeriesStore


# --------------------------------------------------------------- sampler

def _write(path, text):
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(text)


def _fake_proc(tmp_path, busy, total, pid_ticks):
    """Minimal /proc with one aggregate cpu line and one worker pid."""
    idle = total - busy
    _write(tmp_path / "proc" / "stat",
           f"cpu  {busy} 0 0 {idle} 0 0 0 0 0 0\n"
           "cpu0 0 0 0 0 0 0 0 0 0 0\n")
    _write(tmp_path / "proc" / "meminfo",
           "MemTotal:       16384 kB\n"
           "MemFree:         4096 kB\n"
           "MemAvailable:    8192 kB\n")
    half = pid_ticks // 2
    _write(tmp_path / "proc" / "4242" / "stat",
           f"4242 (worker main) S 1 1 1 0 -1 4194304 0 0 0 0 "
           f"{half} {pid_ticks - half} 0 0 20 0 1 0 0 0 0\n")
    _write(tmp_path / "proc" / "4242" / "statm",
           "10000 2500 500 1 0 9000 0\n")


def _fake_cgroup(tmp_path, usage_usec):
    cg = tmp_path / "cg"
    _write(cg / "cpu.stat",
           f"usage_usec {usage_usec}\nuser_usec 1\nsystem_usec 1\n")
    _write(cg / "memory.current", "123456\n")
    _write(cg / "cpu.pressure",
           "some avg10=1.50 avg60=0.80 avg300=0.10 total=12345\n")
    _write(cg / "memory.pressure",
           "some avg10=0.25 avg60=0.10 avg300=0.00 total=99\n")
    return str(cg)


def test_hw_sampler_fake_proc_tree(tmp_path):
    import os
    hz = os.sysconf("SC_CLK_TCK")
    page = os.sysconf("SC_PAGE_SIZE")
    clock = [100.0]
    _fake_proc(tmp_path, busy=200, total=1000, pid_ticks=0)
    cg = _fake_cgroup(tmp_path, usage_usec=1_000_000)
    sampler = HardwareSampler(
        procfs=str(tmp_path / "proc"), cgroup_dir=cg,
        workers=lambda: [{"worker_id": "deadbeef" * 4, "pid": 4242,
                          "state": "actor"}],
        arena_stats=lambda: {"bytes_used": 10, "capacity": 100,
                             "num_objects": 2, "total_evicted": 1},
        clock=lambda: clock[0])

    first = {s["metric"]: s for s in sampler.sample()}
    # deltas need a prior pass: no percentages yet, levels present
    assert "node_cpu_percent" not in first
    assert "worker_cpu_percent" not in first
    assert first["node_mem_total_bytes"]["value"] == 16384 * 1024
    assert first["node_mem_used_bytes"]["value"] == (16384 - 8192) * 1024
    assert first["worker_rss_bytes"]["value"] == 2500 * page
    assert first["worker_rss_bytes"]["tags"] == {
        "worker": "deadbeefdead", "state": "actor"}
    assert first["object_store_used_bytes"]["value"] == 10
    assert first["object_store_capacity_bytes"]["value"] == 100
    assert first["object_store_num_objects"]["value"] == 2
    assert first["object_store_evictions"]["value"] == 1
    assert first["cgroup_mem_current_bytes"]["value"] == 123456
    assert first["cgroup_cpu_pressure_avg10"]["value"] == 1.50
    assert first["cgroup_memory_pressure_avg10"]["value"] == 0.25
    assert all("ts" in s for s in first.values())

    # advance 2s of wall clock: node busy +200/+800 ticks -> 25%,
    # worker +hz ticks over 2s -> 50%, cgroup +1s of cpu over 2s -> 50%
    clock[0] += 2.0
    _fake_proc(tmp_path, busy=400, total=1800, pid_ticks=2 * hz)
    _fake_cgroup(tmp_path, usage_usec=2_000_000)
    second = {s["metric"]: s for s in sampler.sample()}
    assert second["node_cpu_percent"]["value"] == 25.0
    assert second["worker_cpu_percent"]["value"] == pytest.approx(
        100.0, abs=0.5)
    assert second["cgroup_cpu_percent"]["value"] == pytest.approx(
        50.0, abs=0.5)

    # a worker that exits is pruned from the delta table
    sampler._workers = lambda: []
    sampler.sample()
    assert sampler._prev_pid_ticks == {}


def test_hw_sampler_probe_isolation(tmp_path, caplog):
    """One raising probe loses only its own gauges for the pass — the
    rest of the batch still lands — and it warns once, not per period."""
    import logging
    _fake_proc(tmp_path, busy=200, total=1000, pid_ticks=0)
    sampler = HardwareSampler(procfs=str(tmp_path / "proc"))

    def boom():
        raise RuntimeError("probe exploded")

    sampler._node_cpu = boom  # injected fault in the first probe
    with caplog.at_level(logging.WARNING, "ray_tpu.runtime.hw_sampler"):
        first = {s["metric"] for s in sampler.sample()}
        second = {s["metric"] for s in sampler.sample()}
    # other probes survived both passes
    assert "node_mem_total_bytes" in first
    assert "node_mem_total_bytes" in second
    warnings = [r for r in caplog.records if "node_cpu" in r.getMessage()]
    assert len(warnings) == 1  # warn-once, repeats suppressed


def test_hw_sampler_pid_reuse_drops_sample(tmp_path):
    """pid reused between passes (cpu tick counter restarts near 0) must
    DROP the sample — never emit a huge-negative or garbage delta — and
    the fresh baseline seeds the next pass normally."""
    import os
    hz = os.sysconf("SC_CLK_TCK")
    clock = [100.0]
    _fake_proc(tmp_path, busy=200, total=1000, pid_ticks=50 * hz)
    sampler = HardwareSampler(
        procfs=str(tmp_path / "proc"),
        workers=lambda: [{"worker_id": "w1", "pid": 4242, "state": "a"}],
        clock=lambda: clock[0])
    sampler.sample()  # baseline at 50*hz ticks

    # new process under the same pid: ticks restarted from ~0
    clock[0] += 2.0
    _fake_proc(tmp_path, busy=400, total=1800, pid_ticks=1 * hz)
    reused = {s["metric"] for s in sampler.sample()}
    assert "worker_cpu_percent" not in reused  # dropped, not garbage
    # but a fresh baseline was recorded: the NEXT delta is valid again
    clock[0] += 2.0
    _fake_proc(tmp_path, busy=600, total=2600, pid_ticks=3 * hz)
    third = {s["metric"]: s for s in sampler.sample()}
    assert third["worker_cpu_percent"]["value"] == pytest.approx(
        100.0, abs=0.5)


def test_hw_sampler_cpu_percent_clamped(tmp_path):
    """A tick-counter hiccup can't graph a 4000%-CPU worker: the emitted
    percentage is clamped to 100 * ncpu."""
    import os
    hz = os.sysconf("SC_CLK_TCK")
    clock = [100.0]
    _fake_proc(tmp_path, busy=200, total=1000, pid_ticks=0)
    sampler = HardwareSampler(
        procfs=str(tmp_path / "proc"),
        workers=lambda: [{"worker_id": "w1", "pid": 4242, "state": "a"}],
        clock=lambda: clock[0])
    sampler.sample()
    # 1000*hz ticks in 2s of wall clock => 50000% uncapped
    clock[0] += 2.0
    _fake_proc(tmp_path, busy=400, total=1800, pid_ticks=1000 * hz)
    got = {s["metric"]: s for s in sampler.sample()}
    assert got["worker_cpu_percent"]["value"] <= 100.0 * sampler._ncpu


# ------------------------------------------------------------------ rings

def test_timeseries_ring_eviction():
    store = TimeSeriesStore(maxlen=4, max_series=3)
    for i in range(10):
        store.append("nodeA", "cpu", float(i), ts=1000.0 + i)
    (series,) = store.dump()
    # ring keeps exactly the newest maxlen points, oldest first
    assert [v for _, v in series["points"]] == [6.0, 7.0, 8.0, 9.0]
    assert [t for t, _ in series["points"]] == [1006.0, 1007.0,
                                                1008.0, 1009.0]

    # distinct tag sets are distinct series; exceeding max_series evicts
    # the least-recently-appended whole series (nodeA/cpu is oldest)
    store.append("nodeB", "cpu", 1.0, ts=2000.0)
    store.append("nodeB", "mem", 2.0, ts=2000.0)
    store.append("nodeB", "cpu", 3.0, ts=2001.0, tags={"worker": "w1"})
    assert store.num_series() == 3
    assert store.dump(node="nodeA") == []
    # filters: node prefix + exact metric + last-N
    assert len(store.dump(node="nodeB", metric="cpu")) == 2
    store.append("nodeB", "cpu", 4.0, ts=2002.0)
    (s,) = [r for r in store.dump(node="nodeB", metric="cpu", last=1)
            if not r["tags"]]
    assert s["points"] == [(2002.0, 4.0)]

    # latest(): newest point per series, age cutoff drops stale series
    latest = store.latest()
    assert {(s["metric"], s["value"]) for s in latest} == {
        ("cpu", 4.0), ("cpu", 3.0), ("mem", 2.0)}
    assert store.latest(max_age_s=0.001) == []  # ts 2002 is ancient

    # ingest skips malformed entries instead of raising
    n = store.ingest("nodeC", [{"metric": "ok", "value": 1.0},
                               {"value": 2.0}, "junk", None,
                               {"metric": "bad", "value": "NaNsense"}])
    assert n >= 1
    assert store.dump(node="nodeC", metric="ok")


# ------------------------------------------------------------- prometheus

def test_prometheus_exposition_golden_round_trip():
    metrics_mod.clear_registry()
    try:
        c = metrics_mod.Counter("reqs_total", description="total requests",
                                tag_keys=("route",))
        c.inc(3, tags={"route": "/a"})
        c.inc(2, tags={"route": '/b "quoted"\nline'})
        g = metrics_mod.Gauge("temp", description="temperature")
        g.set(36.6)
        h = metrics_mod.Histogram("lat", description="latency",
                                  boundaries=(0.1, 1.0, 10.0),
                                  tag_keys=("op",))
        for v in (0.05, 0.5, 0.5, 5.0, 50.0):
            h.observe(v, tags={"op": "get"})
        agg = metrics_mod.aggregate({"w0": metrics_mod.snapshot(),
                                     "w1": metrics_mod.snapshot()})
    finally:
        metrics_mod.clear_registry()
    hw = [{"node": "a" * 32, "metric": "node_cpu_percent", "tags": {},
           "ts": 1.0, "value": 12.5},
          {"node": "a" * 32, "metric": "worker_rss_bytes",
           "tags": {"worker": "w12", "state": "idle"},
           "ts": 1.0, "value": 4096.0}]
    text = prometheus.render(agg, hw)

    fams = prometheus.parse(text)
    assert fams["reqs_total"]["type"] == "counter"
    by_route = {s[1]["route"]: s[2]
                for s in fams["reqs_total"]["samples"]}
    # two-worker aggregate sums counters; escaped label round-trips
    assert by_route["/a"] == 6.0
    assert by_route['/b "quoted"\nline'] == 4.0
    assert fams["temp"]["samples"][0][2] == 36.6

    assert fams["lat"]["type"] == "histogram"
    buckets = {s[1]["le"]: s[2] for s in fams["lat"]["samples"]
               if s[0] == "lat_bucket"}
    # per-bucket counts (1,2,1,1) x2 workers -> CUMULATIVE 2,6,8; +Inf=n
    assert buckets == {"0.1": 2.0, "1": 6.0, "10": 8.0, "+Inf": 10.0}
    le_order = [s[2] for s in fams["lat"]["samples"]
                if s[0] == "lat_bucket"]
    assert le_order == sorted(le_order), "buckets must be cumulative"
    (count,) = [s[2] for s in fams["lat"]["samples"] if s[0] == "lat_count"]
    (total,) = [s[2] for s in fams["lat"]["samples"] if s[0] == "lat_sum"]
    assert count == 10.0
    assert total == pytest.approx(2 * sum((0.05, 0.5, 0.5, 5.0, 50.0)))

    # hardware series render as gauges labeled by node + own tags
    assert fams["node_cpu_percent"]["samples"] == [
        ("node_cpu_percent", {"node": "a" * 12}, 12.5)]
    (rss,) = fams["worker_rss_bytes"]["samples"]
    assert rss[1] == {"node": "a" * 12, "worker": "w12", "state": "idle"}

    # every non-comment line must match the exposition grammar (parse
    # raises otherwise) and names must be prometheus-safe
    assert prometheus.sanitize_name("serve latency (s)") == \
        "serve_latency__s_"


def test_prometheus_histogram_tag_escaping_round_trip():
    """Histogram TAG values with every escape-worthy character survive
    render -> parse intact on bucket/sum/count lines alike (the serving
    histograms carry deployment/outcome tags from user-chosen names)."""
    metrics_mod.clear_registry()
    nasty_dep = 'llm "v2"\\canary\nblue'
    nasty_out = 'time\\out "hard"'
    try:
        h = metrics_mod.Histogram(
            "probe_latency_seconds", description="escape probe",
            boundaries=(0.1, 1.0), tag_keys=("deployment", "outcome"))
        h.observe(0.05, tags={"deployment": nasty_dep,
                              "outcome": nasty_out})
        h.observe(5.0, tags={"deployment": nasty_dep,
                             "outcome": nasty_out})
        agg = metrics_mod.aggregate({"w0": metrics_mod.snapshot()})
    finally:
        metrics_mod.clear_registry()
    fams = prometheus.parse(prometheus.render(agg))
    samples = fams["probe_latency_seconds"]["samples"]
    assert samples, fams
    for name, labels, _ in samples:
        assert labels["deployment"] == nasty_dep, (name, labels)
        assert labels["outcome"] == nasty_out, (name, labels)
    buckets = {s[1]["le"]: s[2] for s in samples
               if s[0] == "probe_latency_seconds_bucket"}
    assert buckets == {"0.1": 1.0, "1": 1.0, "+Inf": 2.0}


# ------------------------------------------------- live cluster surfaces

@pytest.fixture(scope="module")
def cluster_rt():
    rt.init(num_cpus=2, _system_config={
        "object_store_memory_bytes": 64 * 1024 * 1024,
        "metrics_export_period_s": 0.2,
        "hw_sampler_period_s": 0.3,
    })
    yield rt
    rt.shutdown()


def _get(url, timeout=30):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.headers.get("Content-Type", ""), r.read()


def test_metrics_endpoint_smoke(cluster_rt):
    """Acceptance: GET /metrics returns valid exposition text containing
    the submit_to_start histogram (cumulative buckets + _sum/_count) and
    at least one per-node hardware gauge."""
    from ray_tpu.core.worker import global_worker
    from ray_tpu.dashboard import Dashboard

    @rt.remote
    def work(i):
        return i * 2

    assert rt.get([work.remote(i) for i in range(8)], timeout=60) == \
        [i * 2 for i in range(8)]

    dash = Dashboard(global_worker.backend.head_addr)
    base = f"http://127.0.0.1:{dash.port}"
    try:
        # poll: worker telemetry flush (0.2s) and the node's hardware
        # sampler (0.3s, needs 2 passes for CPU%) land asynchronously
        deadline = time.monotonic() + 30
        fams = {}
        while time.monotonic() < deadline:
            ctype, body = _get(f"{base}/metrics")
            assert "text/plain" in ctype and "version=0.0.4" in ctype
            fams = prometheus.parse(body.decode())
            if "submit_to_start" in fams and any(
                    f in fams for f in ("node_cpu_percent",
                                        "worker_rss_bytes",
                                        "node_mem_used_bytes")):
                break
            time.sleep(0.3)
        assert fams.get("submit_to_start", {}).get("type") == "histogram", \
            f"families: {sorted(fams)}"
        samples = fams["submit_to_start"]["samples"]
        buckets = [(s[1]["le"], s[2]) for s in samples
                   if s[0] == "submit_to_start_bucket"]
        assert buckets, samples
        vals = [v for _, v in buckets]
        assert vals == sorted(vals), "bucket counts must be cumulative"
        assert buckets[-1][0] == "+Inf"
        (n,) = [s[2] for s in samples if s[0] == "submit_to_start_count"]
        assert n >= 8 and buckets[-1][1] == n
        assert any(s[0] == "submit_to_start_sum" for s in samples)

        hw = [f for f in ("node_cpu_percent", "worker_rss_bytes",
                          "node_mem_used_bytes") if f in fams]
        assert hw, f"no hardware gauge exported: {sorted(fams)}"
        for fam in hw:
            for s in fams[fam]["samples"]:
                assert s[1].get("node"), s

        # /api/timeseries: full rings as JSON, plus filtered views
        _, body = _get(f"{base}/api/timeseries")
        series = json.loads(body)
        assert isinstance(series, list) and series
        row = series[0]
        assert {"node", "metric", "tags", "points"} <= set(row)
        assert all(len(p) == 2 for p in row["points"])
        metric = row["metric"]
        _, body = _get(f"{base}/api/timeseries?metric={metric}&last=1")
        filtered = json.loads(body)
        assert filtered and all(r["metric"] == metric and
                                len(r["points"]) == 1 for r in filtered)
        _, body = _get(f"{base}/api/timeseries?latest=1")
        latest = json.loads(body)
        assert latest and all("value" in r and "ts" in r for r in latest)
    finally:
        dash.stop()


def test_timeseries_dump_and_top_two_node_e2e():
    """timeseries_dump aggregates rings from BOTH node daemons, and the
    `top` CLI renders a node/worker table against the live cluster."""
    import io
    import os
    from contextlib import redirect_stdout

    from ray_tpu.core import config as config_mod
    from ray_tpu.runtime.cluster_backend import start_head, start_node
    from ray_tpu.runtime.protocol import RpcClient, RpcError
    from ray_tpu.scripts import cli

    session = os.urandom(4).hex()
    head_proc, address = start_head(session)
    # spawned daemons inherit GlobalConfig — tighten the sampler period
    # just for the children, then restore
    old_period = config_mod.GlobalConfig.hw_sampler_period_s
    config_mod.GlobalConfig.hw_sampler_period_s = 0.3
    try:
        nodes = [start_node(address, session, resources={"CPU": 1.0})
                 for _ in range(2)]
    finally:
        config_mod.GlobalConfig.hw_sampler_period_s = old_period
    probe = RpcClient(address, name="telemetry-e2e")
    try:
        deadline = time.monotonic() + 60
        sampled_nodes = set()
        while time.monotonic() < deadline:
            try:
                rows = probe.call("timeseries_dump",
                                  {"metric": "node_mem_used_bytes"},
                                  timeout=5)
                sampled_nodes = {r["node"] for r in rows}
            except RpcError:
                sampled_nodes = set()
            if len(sampled_nodes) >= 2:
                break
            time.sleep(0.3)
        assert len(sampled_nodes) >= 2, \
            f"both daemons must push hardware samples: {sampled_nodes}"
        # ring points accumulate over successive sampler periods
        (ring,) = probe.call("timeseries_dump",
                             {"node": sorted(sampled_nodes)[0],
                              "metric": "node_mem_used_bytes"}, timeout=5)
        assert len(ring["points"]) >= 1

        buf = io.StringIO()
        with redirect_stdout(buf):
            assert cli.main(["top", "--address", address]) == 0
        out = buf.getvalue()
        assert "NODE" in out and "MEM" in out
        for nid in sampled_nodes:
            assert nid[:12] in out, out
        assert "nodes 2/2" in out, out
    finally:
        probe.close()
        for p in nodes:
            p.terminate()
        head_proc.terminate()
        for p in nodes:
            p.wait(timeout=10)
        head_proc.wait(timeout=10)
