"""Tune over the multiprocess cluster runtime: trials are real actor
processes with reserved CPU resources, results stream back per-iteration,
and concurrency is capped by cluster capacity."""

import os

import pytest

import ray_tpu as rt
from ray_tpu import tune


@pytest.fixture(scope="module")
def cluster_rt():
    rt.init(num_cpus=3, _system_config={
        "object_store_memory_bytes": 128 * 1024 * 1024,
    })
    yield rt
    rt.shutdown()


def test_trials_run_as_processes(cluster_rt):
    def trainable(cfg):
        for i in range(3):
            tune.report({"score": cfg["x"] + i, "pid": os.getpid()})

    tuner = tune.Tuner(
        trainable,
        param_space={"x": tune.grid_search([10, 20, 30])},
        tune_config=tune.TuneConfig(metric="score", mode="max"))
    grid = tuner.fit()
    assert all(t.status == tune.TrialStatus.TERMINATED for t in grid.trials)
    pids = {t.last_result["pid"] for t in grid.trials}
    assert os.getpid() not in pids, "trials must run out-of-process"
    assert grid.get_best_result().config["x"] == 30
