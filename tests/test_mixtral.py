"""Mixtral MoE model family on the virtual 8-device CPU mesh.

Coverage mirrors test_models.py's llama suite: single-device shape/finite +
training sanity, spec alignment, and expert-parallel (ep) forward parity
against the dense routing reference (SURVEY §2.6 EP row exercised through
a FULL model, not just the layer)."""

import functools

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ray_tpu.models import mixtral


def make_inputs(cfg, B=2, L=16, seed=0):
    return jax.random.randint(jax.random.PRNGKey(seed), (B, L), 0,
                              cfg.vocab_size)


class TestMixtralSingleDevice:
    def test_forward_shape_and_finite(self):
        cfg = mixtral.MixtralConfig.tiny(dtype=jnp.float32)
        params = mixtral.init_params(cfg, jax.random.PRNGKey(0))
        tokens = make_inputs(cfg)
        logits = jax.jit(functools.partial(mixtral.forward, cfg=cfg))(
            params, tokens)
        assert logits.shape == (2, 16, cfg.vocab_size)
        assert np.isfinite(np.asarray(logits)).all()

    def test_loss_decreases_with_sgd(self):
        cfg = mixtral.MixtralConfig.tiny(dtype=jnp.float32)
        params = mixtral.init_params(cfg, jax.random.PRNGKey(0))
        tokens = make_inputs(cfg, B=4, L=16)
        loss_grad = jax.jit(jax.value_and_grad(
            functools.partial(mixtral.loss_fn, cfg=cfg)))
        l0, g = loss_grad(params, tokens)
        assert np.isfinite(float(l0))
        params2 = jax.tree.map(lambda p, gi: p - 0.3 * gi, params, g)
        l1, _ = loss_grad(params2, tokens)
        assert float(l1) < float(l0)

    def test_param_specs_align(self):
        cfg = mixtral.MixtralConfig.tiny()
        params = mixtral.init_params(cfg, jax.random.PRNGKey(0))
        specs = mixtral.param_specs(cfg)
        jax.tree.map(lambda p, s: None, params, specs)  # same structure
        flat_p = jax.tree.leaves(params)
        flat_s = jax.tree.leaves(specs,
                                 is_leaf=lambda x: isinstance(x, P))
        for p, s in zip(flat_p, flat_s):
            assert len(s) <= p.ndim

    def test_active_vs_total_params(self):
        cfg = mixtral.MixtralConfig.tiny()
        assert mixtral.active_params(cfg) < mixtral.num_params(cfg)
        # 8x7B headline sanity: ~13B active of ~47B total
        big = mixtral.MixtralConfig.mixtral_8x7b()
        total = mixtral.num_params(big)
        active = mixtral.active_params(big)
        assert 40e9 < total < 55e9
        assert 10e9 < active < 16e9


class TestMixtralExpertParallel:
    @pytest.fixture(scope="class")
    def mesh(self):
        devices = np.array(jax.devices()[:8]).reshape(2, 4)
        return Mesh(devices, ("dp", "ep"))

    def test_ep_forward_matches_dense(self, mesh):
        """ep=4 all_to_all dispatch == dense per-expert loop (large
        capacity factor so no tokens drop)."""
        cfg = mixtral.MixtralConfig.tiny(dtype=jnp.float32, remat=False,
                                         capacity_factor=8.0)
        params = mixtral.init_params(cfg, jax.random.PRNGKey(1))
        tokens = make_inputs(cfg, B=4, L=16, seed=3)

        dense = jax.jit(functools.partial(mixtral.forward, cfg=cfg))(
            params, tokens)

        specs = mixtral.param_specs(cfg)

        def drop_non_mesh_axes(s):
            return P(*[ax if ax in ("dp", "ep") else None for ax in s])

        sharded_specs = jax.tree.map(drop_non_mesh_axes, specs,
                                     is_leaf=lambda x: isinstance(x, P))
        sp = jax.device_put(params, jax.tree.map(
            lambda s: NamedSharding(mesh, s), sharded_specs,
            is_leaf=lambda x: isinstance(x, P)))
        st = jax.device_put(tokens, NamedSharding(mesh, P("dp", None)))
        with mesh:
            ep_out = jax.jit(functools.partial(
                mixtral.forward, cfg=cfg, mesh=mesh))(sp, st)
        np.testing.assert_allclose(np.asarray(ep_out), np.asarray(dense),
                                   rtol=2e-3, atol=2e-3)

    def test_ep_train_step_decreases_loss(self, mesh):
        cfg = mixtral.MixtralConfig.tiny(dtype=jnp.float32, remat=False)
        params = mixtral.init_params(cfg, jax.random.PRNGKey(0))
        tokens = make_inputs(cfg, B=4, L=16)
        with mesh:
            loss_grad = jax.jit(jax.value_and_grad(functools.partial(
                mixtral.loss_fn, cfg=cfg, mesh=mesh)))
            l0, g = loss_grad(params, tokens)
            params2 = jax.tree.map(lambda p, gi: p - 0.3 * gi, params, g)
            l1, _ = loss_grad(params2, tokens)
        assert np.isfinite(float(l0))
        assert float(l1) < float(l0)
