"""Mixtral MoE model family on the virtual 8-device CPU mesh.

Coverage mirrors test_models.py's llama suite: single-device shape/finite +
training sanity, spec alignment, and expert-parallel (ep) forward parity
against the dense routing reference (SURVEY §2.6 EP row exercised through
a FULL model, not just the layer)."""

import functools

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ray_tpu.models import mixtral


def make_inputs(cfg, B=2, L=16, seed=0):
    return jax.random.randint(jax.random.PRNGKey(seed), (B, L), 0,
                              cfg.vocab_size)


class TestMixtralSingleDevice:
    def test_forward_shape_and_finite(self):
        cfg = mixtral.MixtralConfig.tiny(dtype=jnp.float32)
        params = mixtral.init_params(cfg, jax.random.PRNGKey(0))
        tokens = make_inputs(cfg)
        logits = jax.jit(functools.partial(mixtral.forward, cfg=cfg))(
            params, tokens)
        assert logits.shape == (2, 16, cfg.vocab_size)
        assert np.isfinite(np.asarray(logits)).all()

    def test_loss_decreases_with_sgd(self):
        cfg = mixtral.MixtralConfig.tiny(dtype=jnp.float32)
        params = mixtral.init_params(cfg, jax.random.PRNGKey(0))
        tokens = make_inputs(cfg, B=4, L=16)
        loss_grad = jax.jit(jax.value_and_grad(
            functools.partial(mixtral.loss_fn, cfg=cfg)))
        l0, g = loss_grad(params, tokens)
        assert np.isfinite(float(l0))
        params2 = jax.tree.map(lambda p, gi: p - 0.3 * gi, params, g)
        l1, _ = loss_grad(params2, tokens)
        assert float(l1) < float(l0)

    def test_param_specs_align(self):
        cfg = mixtral.MixtralConfig.tiny()
        params = mixtral.init_params(cfg, jax.random.PRNGKey(0))
        specs = mixtral.param_specs(cfg)
        jax.tree.map(lambda p, s: None, params, specs)  # same structure
        flat_p = jax.tree.leaves(params)
        flat_s = jax.tree.leaves(specs,
                                 is_leaf=lambda x: isinstance(x, P))
        for p, s in zip(flat_p, flat_s):
            assert len(s) <= p.ndim

    def test_active_vs_total_params(self):
        cfg = mixtral.MixtralConfig.tiny()
        assert mixtral.active_params(cfg) < mixtral.num_params(cfg)
        # 8x7B headline sanity: ~13B active of ~47B total
        big = mixtral.MixtralConfig.mixtral_8x7b()
        total = mixtral.num_params(big)
        active = mixtral.active_params(big)
        assert 40e9 < total < 55e9
        assert 10e9 < active < 16e9


class TestMixtralExpertParallel:
    @pytest.fixture(scope="class")
    def mesh(self):
        devices = np.array(jax.devices()[:8]).reshape(2, 4)
        return Mesh(devices, ("dp", "ep"))

    def test_ep_forward_matches_dense(self, mesh):
        """ep=4 all_to_all dispatch == dense per-expert loop (large
        capacity factor so no tokens drop)."""
        cfg = mixtral.MixtralConfig.tiny(dtype=jnp.float32, remat=False,
                                         capacity_factor=8.0)
        params = mixtral.init_params(cfg, jax.random.PRNGKey(1))
        tokens = make_inputs(cfg, B=4, L=16, seed=3)

        dense = jax.jit(functools.partial(mixtral.forward, cfg=cfg))(
            params, tokens)

        specs = mixtral.param_specs(cfg)

        def drop_non_mesh_axes(s):
            return P(*[ax if ax in ("dp", "ep") else None for ax in s])

        sharded_specs = jax.tree.map(drop_non_mesh_axes, specs,
                                     is_leaf=lambda x: isinstance(x, P))
        sp = jax.device_put(params, jax.tree.map(
            lambda s: NamedSharding(mesh, s), sharded_specs,
            is_leaf=lambda x: isinstance(x, P)))
        st = jax.device_put(tokens, NamedSharding(mesh, P("dp", None)))
        with mesh:
            ep_out = jax.jit(functools.partial(
                mixtral.forward, cfg=cfg, mesh=mesh))(sp, st)
        np.testing.assert_allclose(np.asarray(ep_out), np.asarray(dense),
                                   rtol=2e-3, atol=2e-3)

    def test_ep_train_step_decreases_loss(self, mesh):
        cfg = mixtral.MixtralConfig.tiny(dtype=jnp.float32, remat=False)
        params = mixtral.init_params(cfg, jax.random.PRNGKey(0))
        tokens = make_inputs(cfg, B=4, L=16)
        with mesh:
            loss_grad = jax.jit(jax.value_and_grad(functools.partial(
                mixtral.loss_fn, cfg=cfg, mesh=mesh)))
            l0, g = loss_grad(params, tokens)
            params2 = jax.tree.map(lambda p, gi: p - 0.3 * gi, params, g)
            l1, _ = loss_grad(params2, tokens)
        assert np.isfinite(float(l0))
        assert float(l1) < float(l0)


class TestMixtralRematAndOverlap:
    """ISSUE 7 parity guards on the MoE family: selective remat is a
    pure lever, and the overlap-scheduled fsdp step matches GSPMD on the
    CE term (aux_loss_coef=0 — the Switch load-balance statistics are
    per-shard in the manual path, a documented semantic difference)."""

    def test_selective_remat_matches_full(self):
        import dataclasses
        cfg = mixtral.MixtralConfig.tiny(dtype=jnp.float32)
        cfg_sel = dataclasses.replace(cfg, remat_policy="selective")
        params = mixtral.init_params(cfg, jax.random.PRNGKey(0))
        tokens = make_inputs(cfg, B=2, L=16)
        vag = lambda c: jax.jit(jax.value_and_grad(functools.partial(
            mixtral.loss_fn, cfg=c)))
        l_ref, g_ref = vag(cfg)(params, tokens)
        l_sel, g_sel = vag(cfg_sel)(params, tokens)
        assert float(l_sel) == pytest.approx(float(l_ref), abs=1e-6)
        for got, ref in zip(jax.tree.leaves(g_sel), jax.tree.leaves(g_ref)):
            np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                       rtol=1e-5, atol=1e-6)

    def _place(self, cfg, mesh, B=8, L=16):
        params = mixtral.init_params(cfg, jax.random.PRNGKey(0))
        specs = mixtral.param_specs(cfg)

        def drop_non_mesh_axes(s):  # specs name ep; this mesh doesn't
            return P(*[ax if ax in mesh.shape else None for ax in s])

        specs = jax.tree.map(drop_non_mesh_axes, specs,
                             is_leaf=lambda x: isinstance(x, P))
        params = jax.device_put(
            params, jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                                 is_leaf=lambda x: isinstance(x, P)))
        tokens = jax.device_put(
            make_inputs(cfg, B, L),
            NamedSharding(mesh, P(("dp", "fsdp"), None)))
        return params, tokens

    def test_overlap_ce_matches_gspmd(self):
        import dataclasses
        from ray_tpu.parallel import MeshSpec, build_mesh
        mesh = build_mesh(MeshSpec(dp=2, fsdp=4))
        # aux_loss_coef=0: exact CE parity (the aux term averages
        # per-shard routing stats in the manual path)
        cfg = mixtral.MixtralConfig.tiny(dtype=jnp.float32,
                                         aux_loss_coef=0.0)
        cfg_ov = dataclasses.replace(cfg, fsdp_overlap=True)
        params, tokens = self._place(cfg, mesh)
        vag = lambda c: jax.jit(jax.value_and_grad(functools.partial(
            mixtral.loss_fn, cfg=c, mesh=mesh)))
        l_ref, g_ref = vag(cfg)(params, tokens)
        l_ov, g_ov = vag(cfg_ov)(params, tokens)
        assert float(l_ov) == pytest.approx(float(l_ref), abs=1e-5)
        for got, ref in zip(jax.tree.leaves(g_ov), jax.tree.leaves(g_ref)):
            np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                       rtol=1e-4, atol=1e-5)

    def test_overlap_default_aux_is_finite(self):
        from ray_tpu.parallel import MeshSpec, build_mesh
        mesh = build_mesh(MeshSpec(dp=2, fsdp=4))
        cfg = mixtral.MixtralConfig.tiny(dtype=jnp.float32,
                                         fsdp_overlap=True)
        params, tokens = self._place(cfg, mesh)
        loss = jax.jit(functools.partial(
            mixtral.loss_fn, cfg=cfg, mesh=mesh))(params, tokens)
        assert np.isfinite(float(loss))
