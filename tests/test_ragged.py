"""Ragged paged-attention tests: the single-dispatch mixed
prefill+decode kernel (ops/paged_attention.py ragged_* APIs) against a
dense per-token oracle, across GQA configs, page-boundary-straddling
chunks, degenerate single-row batches, and int8-quantized KV pages.

The Pallas kernel runs in interpret mode (pallas_interpret marker) so
the kernel logic — scalar-prefetched page indexing, per-token causal
visibility, online softmax across the page grid axis — is exercised in
tier-1 on CPU.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.ops.int8 import dequantize_kv, quantize_kv
from ray_tpu.ops.paged_attention import (_ragged_attention_pallas,
                                         paged_attention_reference,
                                         ragged_paged_attention,
                                         ragged_paged_attention_reference,
                                         write_ragged_kv)


def _dense_oracle(q, kp, vp, pt, q_start, q_len, kv_len,
                  k_scale=None, v_scale=None):
    """Per-token dense attention: gather row pages, causal-mask by the
    token's absolute position, fp32 softmax. Padding tokens -> 0."""
    q, kp, vp = map(lambda a: np.asarray(a, np.float64), (q, kp, vp))
    if k_scale is not None:
        kp = kp * np.asarray(k_scale, np.float64)[..., None]
        vp = vp * np.asarray(v_scale, np.float64)[..., None]
    T, Hq, D = q.shape
    Hkv, ps = kp.shape[1], kp.shape[2]
    g = Hq // Hkv
    out = np.zeros((T, Hq, D))
    for r in range(len(q_start)):
        for j in range(int(q_len[r])):
            t = int(q_start[r]) + j
            vis = int(kv_len[r]) - int(q_len[r]) + j + 1
            pages = np.asarray(pt[r])[: -(-vis // ps)]
            k = kp[pages].transpose(1, 0, 2, 3).reshape(Hkv, -1, D)[:, :vis]
            v = vp[pages].transpose(1, 0, 2, 3).reshape(Hkv, -1, D)[:, :vis]
            qg = q[t].reshape(Hkv, g, D)
            s = np.einsum("hgd,htd->hgt", qg, k) * D ** -0.5
            p = np.exp(s - s.max(-1, keepdims=True))
            p /= p.sum(-1, keepdims=True)
            out[t] = np.einsum("hgt,htd->hgd", p, v).reshape(Hq, D)
    return out


def _mixed_batch(key, Hq, Hkv, D, ps=8, pages=12, max_pages=4):
    """2 decode rows + 1 inactive row + 2 prefill chunks, one chunk
    straddling a page boundary (ends mid-page after crossing one)."""
    ks = jax.random.split(key, 3)
    T = 16
    q = jax.random.normal(ks[0], (T, Hq, D), jnp.float32)
    kp = jax.random.normal(ks[1], (pages, Hkv, ps, D), jnp.float32)
    vp = jax.random.normal(ks[2], (pages, Hkv, ps, D), jnp.float32)
    pt = jnp.array([[1, 2, 3, 4], [5, 6, 7, 8], [0, 0, 0, 0],
                    [9, 10, 11, 1], [2, 3, 4, 5]], jnp.int32)
    # rows: decode len 11, decode len 24, inactive, 6-tok chunk ending
    # at kv position 21 (straddles the page-2 -> page-3 boundary), 4-tok
    # chunk fully inside page 0 of its table
    q_start = jnp.array([0, 1, 0, 3, 9], jnp.int32)
    q_len = jnp.array([1, 1, 0, 6, 4], jnp.int32)
    kv_len = jnp.array([11, 24, 0, 21, 4], jnp.int32)
    return q, kp, vp, pt, q_start, q_len, kv_len


@pytest.mark.parametrize("Hq,Hkv", [(8, 8), (8, 4), (8, 1)])
def test_ragged_reference_matches_dense_gqa(Hq, Hkv):
    args = _mixed_batch(jax.random.PRNGKey(Hq * 10 + Hkv), Hq, Hkv, 32)
    want = _dense_oracle(*args)
    got = ragged_paged_attention_reference(*args, max_q_len=6,
                                           decode_rows=2)
    np.testing.assert_allclose(np.asarray(got), want, atol=1e-5)
    # cost hints must be cost-only: no hints, same numbers
    got2 = ragged_paged_attention_reference(*args)
    np.testing.assert_allclose(np.asarray(got2), want, atol=1e-5)
    # padding tokens (owned by no row) must come back exactly zero
    owned = np.zeros(args[0].shape[0], bool)
    for s, l in zip(args[4], args[5]):
        owned[int(s):int(s) + int(l)] = True
    assert np.all(np.asarray(got)[~owned] == 0.0)


@pytest.mark.pallas_interpret
@pytest.mark.parametrize("Hq,Hkv", [(8, 8), (8, 4), (8, 1)])
def test_ragged_pallas_interpret_matches_reference(Hq, Hkv, pallas_interpret):
    D = 128   # lane-width head_dim, the TPU-shaped case
    args = _mixed_batch(jax.random.PRNGKey(Hq + Hkv), Hq, Hkv, D, ps=16)
    ref = ragged_paged_attention_reference(*args)
    out = _ragged_attention_pallas(*args, None, None, D ** -0.5,
                                   interpret=pallas_interpret)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-2)


@pytest.mark.pallas_interpret
def test_ragged_pallas_int8_pages(pallas_interpret):
    Hq, Hkv, D = 8, 4, 128
    q, kp, vp, pt, q_start, q_len, kv_len = _mixed_batch(
        jax.random.PRNGKey(11), Hq, Hkv, D, ps=16)
    kq, ksc = quantize_kv(kp)
    vq, vsc = quantize_kv(vp)
    ref = ragged_paged_attention_reference(q, kq, vq, pt, q_start, q_len,
                                           kv_len, k_scale=ksc,
                                           v_scale=vsc)
    out = _ragged_attention_pallas(q, kq, vq, pt, q_start, q_len, kv_len,
                                   ksc, vsc, D ** -0.5,
                                   interpret=pallas_interpret)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-2)
    # and the int8 path stays close to unquantized attention
    fp = ragged_paged_attention_reference(q, kp, vp, pt, q_start, q_len,
                                          kv_len)
    assert float(jnp.max(jnp.abs(ref - fp))) < 0.05


def test_ragged_single_row_degenerate():
    """R=1 batches — one decode row, then one prefill row — must work
    (the scheduler emits these when the engine idles down)."""
    key = jax.random.PRNGKey(5)
    Hq, Hkv, D, ps = 4, 2, 32, 8
    ks = jax.random.split(key, 3)
    kp = jax.random.normal(ks[1], (6, Hkv, ps, D), jnp.float32)
    vp = jax.random.normal(ks[2], (6, Hkv, ps, D), jnp.float32)
    pt = jnp.array([[1, 2, 3]], jnp.int32)
    q1 = jax.random.normal(ks[0], (1, Hq, D), jnp.float32)
    dec = ragged_paged_attention_reference(
        q1, kp, vp, pt, jnp.array([0]), jnp.array([1]), jnp.array([17]))
    want = _dense_oracle(q1, kp, vp, pt, [0], [1], [17])
    np.testing.assert_allclose(np.asarray(dec), want, atol=1e-5)
    q5 = jax.random.normal(ks[0], (5, Hq, D), jnp.float32)
    pf = ragged_paged_attention_reference(
        q5, kp, vp, pt, jnp.array([0]), jnp.array([5]), jnp.array([13]))
    want = _dense_oracle(q5, kp, vp, pt, [0], [5], [13])
    np.testing.assert_allclose(np.asarray(pf), want, atol=1e-5)


def test_ragged_all_decode_matches_decode_reference():
    """An all-decode ragged batch is exactly the old decode attention:
    the two references must agree bit-for-bit-ish (same math path)."""
    key = jax.random.PRNGKey(9)
    B, Hq, Hkv, D, ps = 4, 8, 4, 64, 8
    ks = jax.random.split(key, 3)
    q = jax.random.normal(ks[0], (B, Hq, D), jnp.float32)
    kp = jax.random.normal(ks[1], (10, Hkv, ps, D), jnp.float32)
    vp = jax.random.normal(ks[2], (10, Hkv, ps, D), jnp.float32)
    pt = jnp.array([[1, 2, 3], [4, 5, 6], [7, 8, 9], [1, 4, 7]],
                   jnp.int32)
    sl = jnp.array([11, 24, 5, 17], jnp.int32)
    dec = paged_attention_reference(q, kp, vp, pt, sl)
    rag = ragged_paged_attention_reference(
        q, kp, vp, pt, jnp.arange(B, dtype=jnp.int32),
        jnp.ones(B, jnp.int32), sl, decode_rows=B, max_q_len=1)
    np.testing.assert_allclose(np.asarray(rag), np.asarray(dec),
                               atol=1e-5)


def test_ragged_dispatcher_interpret_path():
    """The public entry point routes to the kernel (interpret=True on
    CPU) and matches the reference on a mixed batch."""
    args = _mixed_batch(jax.random.PRNGKey(2), 8, 4, 128, ps=16)
    ref = ragged_paged_attention_reference(*args)
    out = ragged_paged_attention(*args, impl="kernel", interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-2)


# ---------------------------------------------------------------- int8 KV


def test_int8_kv_roundtrip_error_bound():
    """Per-(token, head) int8 KV quantization: round-trip error within
    the 1/127 step bound for unit-scale rows, including bf16 scales."""
    x = jax.random.normal(jax.random.PRNGKey(0), (64, 4, 64), jnp.float32)
    q, s = quantize_kv(x)
    assert q.dtype == jnp.int8 and s.shape == (64, 4)
    back = dequantize_kv(q, s)
    err = float(jnp.max(jnp.abs(back - x)))
    # step/2 = amax/254 plus bf16 scale rounding (2^-8 relative)
    amax = float(jnp.max(jnp.abs(x)))
    assert err < amax * (1 / 254 + 2 ** -8) * 1.5, err


def test_write_ragged_kv_fp_and_int8():
    key = jax.random.PRNGKey(4)
    Hkv, ps, D, P, T = 2, 8, 16, 5, 10
    ks = jax.random.split(key, 2)
    k_t = jax.random.normal(ks[0], (T, Hkv, D), jnp.float32)
    v_t = jax.random.normal(ks[1], (T, Hkv, D), jnp.float32)
    page = jnp.array([1, 1, 1, 2, 2, 3, 3, 3, 4, 0], jnp.int32)
    slot = jnp.array([0, 1, 2, 5, 6, 0, 1, 7, 3, 0], jnp.int32)
    # fp path
    kp = jnp.zeros((P, Hkv, ps, D), jnp.float32)
    vp = jnp.zeros_like(kp)
    kp2, vp2, ksc, vsc = write_ragged_kv(kp, vp, k_t, v_t, page, slot)
    assert ksc is None and vsc is None
    for t in range(T):
        np.testing.assert_allclose(
            np.asarray(kp2[page[t], :, slot[t]]), np.asarray(k_t[t]))
        np.testing.assert_allclose(
            np.asarray(vp2[page[t], :, slot[t]]), np.asarray(v_t[t]))
    # int8 path: scatter quantized rows + scales, round-trip bounded
    kq = jnp.zeros((P, Hkv, ps, D), jnp.int8)
    vq = jnp.zeros_like(kq)
    from ray_tpu.ops.int8 import KV_SCALE_DTYPE
    ks8 = jnp.zeros((P, Hkv, ps), KV_SCALE_DTYPE)
    vs8 = jnp.zeros_like(ks8)
    kq2, vq2, ks2, vs2 = write_ragged_kv(kq, vq, k_t, v_t, page, slot,
                                         ks8, vs8)
    assert kq2.dtype == jnp.int8 and ks2.dtype == KV_SCALE_DTYPE
    for t in range(T - 1):   # last token aliases scratch page 0
        got = dequantize_kv(kq2[page[t], :, slot[t]],
                            ks2[page[t], :, slot[t]])
        np.testing.assert_allclose(np.asarray(got), np.asarray(k_t[t]),
                                   atol=2e-2)
        got = dequantize_kv(vq2[page[t], :, slot[t]],
                            vs2[page[t], :, slot[t]])
        np.testing.assert_allclose(np.asarray(got), np.asarray(v_t[t]),
                                   atol=2e-2)
