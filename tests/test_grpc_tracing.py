"""gRPC serve ingress + OTLP tracing export (reference: serve gRPC
proxy in serve/_private/proxy.py; ray.util.tracing OTel integration)."""

import json

import pytest

import ray_tpu
from ray_tpu import serve


@pytest.fixture(scope="module")
def rt():
    ray_tpu.init(num_cpus=4)
    yield
    serve.shutdown()
    ray_tpu.shutdown()


@serve.deployment(num_replicas=1)
class Echo:
    def __call__(self, body):
        return {"echo": body, "who": "grpc"}

    def tokens(self, body):
        for i in range(int(body.get("n", 3))):
            yield {"token": i}


def test_grpc_unary_and_stream(rt):
    grpc = pytest.importorskip("grpc")
    serve.run(Echo.bind())
    ingress = serve.start_grpc_proxy()
    try:
        chan = grpc.insecure_channel(f"127.0.0.1:{ingress.port}")
        call = chan.unary_unary("/raytpu.serve.Ingress/Call")
        reply = json.loads(call(json.dumps(
            {"app": "Echo", "body": {"x": 1}}).encode(), timeout=60))
        assert reply["result"]["echo"] == {"x": 1}
        assert reply["result"]["who"] == "grpc"

        stream = chan.unary_stream("/raytpu.serve.Ingress/Stream")
        items = [json.loads(m)["result"] for m in stream(json.dumps(
            {"app": "Echo", "method": "tokens",
             "body": {"n": 4}}).encode(), timeout=60)]
        assert items == [{"token": i} for i in range(4)]

        # bad requests surface as INVALID_ARGUMENT, not INTERNAL
        with pytest.raises(grpc.RpcError) as ei:
            call(json.dumps({"no_app": True}).encode(), timeout=30)
        assert ei.value.code() == grpc.StatusCode.INVALID_ARGUMENT
        with pytest.raises(grpc.RpcError) as ei:
            call(json.dumps({"app": "NoSuchApp"}).encode(), timeout=30)
        assert ei.value.code() == grpc.StatusCode.INVALID_ARGUMENT
        chan.close()
    finally:
        ingress.stop()


def test_otlp_export(rt, tmp_path):
    from ray_tpu.util import tracing

    @ray_tpu.remote
    def traced_task(x):
        return x + 1

    assert ray_tpu.get([traced_task.remote(i) for i in range(5)],
                       timeout=60) == list(range(1, 6))
    # telemetry flush interval: spans reach the head asynchronously
    import time
    deadline = time.monotonic() + 30
    path = str(tmp_path / "spans.json")
    spans = []
    while time.monotonic() < deadline:
        tracing.export_otlp_file(path)
        doc = json.loads(open(path).read())
        spans = doc["resourceSpans"][0]["scopeSpans"][0]["spans"]
        # wait for THIS test's spans specifically: other tests' serve
        # spans may flush first (telemetry interval lag)
        if sum("traced_task" in sp["name"] for sp in spans) >= 5:
            break
        time.sleep(0.5)
    mine = [sp for sp in spans if "traced_task" in sp["name"]]
    assert len(mine) >= 5, f"only {len(mine)} traced_task spans"
    s = mine[0]
    assert len(s["traceId"]) == 32 and len(s["spanId"]) == 16
    assert int(s["endTimeUnixNano"]) >= int(s["startTimeUnixNano"])
    assert s["status"]["code"] == 1
    attrs = {a["key"]: a["value"]["stringValue"] for a in s["attributes"]}
    assert attrs["rtpu.task_id"]
    svc = doc["resourceSpans"][0]["resource"]["attributes"][0]
    assert svc["value"]["stringValue"] == "ray_tpu"


def test_serve_router_replica_share_trace(rt):
    """A Serve request is one trace: the router records a root span and
    installs it as ambient, so the replica's actor-task span links to it
    via parent_span_id across the process hop."""
    import time

    from ray_tpu.core.worker import global_worker

    @serve.deployment(num_replicas=1)
    class TracedDep:
        def ping(self, x):
            return x + 1

    handle = serve.run(TracedDep.bind())
    assert handle.ping.remote(1).result(timeout=60) == 2
    deadline = time.monotonic() + 30
    events, router, replica = [], None, None
    while time.monotonic() < deadline:
        events = global_worker.backend.head.call("timeline_dump")
        router = next(
            (e for e in events if e.get("kind") == "serve_router"
             and "TracedDep" in e["name"]), None)
        if router is not None:
            replica = next(
                (e for e in events if e.get("kind") == "actor_task"
                 and e.get("trace_id") == router.get("trace_id")
                 and e.get("parent_span_id") == router.get("span_id")),
                None)
        if router is not None and replica is not None:
            break
        time.sleep(0.5)
    assert router is not None, \
        [e["name"] for e in events if e.get("kind") == "serve_router"]
    assert replica is not None, events
    # same trace, replica span parented on the router span
    assert router["trace_id"] == replica["trace_id"]
    assert replica["parent_span_id"] == router["span_id"]


def test_otlp_ids_deterministic():
    from ray_tpu.util.tracing import events_to_otlp
    ev = [{"name": "t", "task_id": "abc", "kind": "task",
           "start": 100.0, "end": 101.0, "ok": True}]
    a = events_to_otlp(ev)
    b = events_to_otlp(ev)
    assert a == b  # re-exports dedup at the collector