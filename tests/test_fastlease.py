"""Native lease pool (transport.cc FastLease): grant/release served inside
the head's C event loop, Python keeping placement/reclaim policy.

Covers the VERDICT r4 #3 design: steady-state acquire hits in C (stats
show hits), release re-pools without Python, disconnect reclaims held
grants, pooled capacity never starves other shapes (drain-on-busy), and
corpse grants are invalidated rather than re-pooled.

Reference semantics matched: raylet lease grant loop
(src/ray/raylet/node_manager.cc:1908) + lease-lifetime-bound-to-owner
reclamation."""

import time

import pytest

import ray_tpu as rt
from ray_tpu.runtime import wire


@pytest.fixture
def cluster():
    rt.init(num_cpus=4, _system_config={
        "object_store_memory_bytes": 128 * 1024 * 1024,
        "lease_idle_linger_s": 0.2,
    })
    yield rt
    rt.shutdown()


def _head_lease_stats():
    """Ask the head process for its native-pool stats via state_dump."""
    from ray_tpu.core.worker import global_worker
    be = global_worker.backend
    dump = be.head.call("state_dump", timeout=10)
    return dump.get("fast_lease") if isinstance(dump, dict) else None


@rt.remote
def tiny(i):
    return i + 1


def test_burst_hits_native_pool(cluster):
    # first burst arms the pool (Python path), second burst acquires in C
    assert rt.get([tiny.remote(i) for i in range(100)]) == \
        [i + 1 for i in range(100)]
    time.sleep(0.6)  # linger: leases release back to the pool
    assert rt.get([tiny.remote(i) for i in range(100)]) == \
        [i + 1 for i in range(100)]
    deadline = time.monotonic() + 10
    stats = None
    while time.monotonic() < deadline:
        stats = _head_lease_stats()
        if stats and stats.get("hits", 0) > 0:
            break
        time.sleep(0.2)
    assert stats is not None, "head did not report fast-lease stats"
    assert stats["hits"] > 0, f"no native acquire ever hit: {stats}"


def test_pool_drains_when_other_shape_needs_capacity(cluster):
    """Pooled 1-CPU grants hold real capacity; a 4-CPU request must drain
    them (drain-on-busy) instead of starving."""
    rt.get([tiny.remote(i) for i in range(50)])
    time.sleep(0.6)  # release to pool

    @rt.remote(num_cpus=4)
    def big():
        return "ran"

    # all 4 CPUs exist only if the pool lets go
    assert rt.get(big.remote(), timeout=30) == "ran"


def test_pool_idle_drain_returns_capacity(cluster):
    rt.get([tiny.remote(i) for i in range(50)])
    # pool idle-drain (fast_lease_idle_drain_s=3) must hand capacity back
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline:
        avail = rt.available_resources()
        if avail.get("CPU", 0) >= 4.0:
            break
        time.sleep(0.5)
    assert rt.available_resources().get("CPU", 0) >= 4.0, \
        "pooled grants never drained back to the cluster"


def test_release_requires_holding_connection(cluster):
    """FOP_LEASE_REL ownership check: only the connection that acquired a
    grant may re-pool it. A foreign conn's release must return status 0
    (sending it down the Python release_lease fallback, which validates
    under the head lock) — otherwise a stale release racing a reconnect
    could hand the same grant to two workers."""
    import pickle

    from ray_tpu.core.worker import global_worker
    from ray_tpu.runtime import protocol_native as _pn
    from ray_tpu.runtime.protocol import RpcClient

    be = global_worker.backend
    if not getattr(be, "_head_fast", False):
        pytest.skip("head fastpath disabled in this build")
    # arm the pool: a burst stocks 1-CPU grants, linger re-pools them
    assert rt.get([tiny.remote(i) for i in range(50)]) == \
        [i + 1 for i in range(50)]
    sig = wire.lease_sig({"CPU": 1.0})
    deadline = time.monotonic() + 15
    status, blob = 0, b""
    while time.monotonic() < deadline:
        status, blob = be.head.call_fast(
            _pn.FAST_LEASE_ACQ, key=_pn._U64.pack(sig), timeout=5)
        if status == 1:
            break
        time.sleep(0.3)
    assert status == 1, "native pool never stocked a 1-CPU grant"
    fast_key = pickle.loads(blob)["fast_key"]

    other = RpcClient(be.head_addr, name="chaos-release")
    try:
        st_foreign, _ = other.call_fast(
            _pn.FAST_LEASE_REL, key=_pn._U64.pack(fast_key), timeout=5)
        assert st_foreign == 0, \
            "a foreign connection re-pooled another conn's held lease"
    finally:
        other.close()
    # the true holder's release still re-pools
    st_holder, _ = be.head.call_fast(
        _pn.FAST_LEASE_REL, key=_pn._U64.pack(fast_key), timeout=5)
    assert st_holder == 1, "holder's own release was refused"


def test_lease_sig_stability():
    # head and client must agree on the shape signature across dict order
    a = wire.lease_sig({"CPU": 1.0, "custom": 2.0})
    b = wire.lease_sig({"custom": 2.0, "CPU": 1.0})
    assert a == b
    assert wire.lease_sig({"CPU": 2.0}) != wire.lease_sig({"CPU": 1.0})
