"""Parallel-layer tests on the 8-device virtual CPU mesh (conftest trick,
mirroring reference fake-multi-node testing — SURVEY.md §4 item (d))."""

import functools

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ray_tpu.parallel import (MeshSpec, build_mesh, ring_attention,
                              ulysses_attention, pipeline_apply)
from ray_tpu.parallel.ring_attention import ring_attention_sharded
from ray_tpu.parallel.ulysses import ulysses_attention_sharded
from ray_tpu.parallel import collectives

from ray_tpu.parallel.mesh import shard_map_compat


def naive_causal_attention(q, k, v):
    D = q.shape[-1]
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32) * D**-0.5,
                   k.astype(jnp.float32))
    L = q.shape[1]
    mask = jnp.tril(jnp.ones((L, L), bool))
    s = jnp.where(mask[None, None], s, float("-inf"))
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))


class TestMeshSpec:
    def test_resolve_fill(self):
        s = MeshSpec(fsdp=-1, tp=2).resolve(8)
        assert s.fsdp == 4 and s.size == 8

    def test_resolve_exact(self):
        s = MeshSpec(dp=2, fsdp=2, tp=2).resolve(8)
        assert s.size == 8

    def test_resolve_mismatch(self):
        with pytest.raises(ValueError):
            MeshSpec(tp=3).resolve(8)

    def test_build_mesh(self):
        mesh = build_mesh(MeshSpec(sp=4, tp=2))
        assert mesh.shape == {"pp": 1, "dp": 1, "fsdp": 1, "sp": 4, "tp": 2}


@pytest.fixture(scope="module")
def sp_mesh():
    return build_mesh(MeshSpec(sp=4, tp=2))


class TestRingAttention:
    def test_matches_naive(self, sp_mesh):
        key = jax.random.PRNGKey(0)
        kq, kk, kv = jax.random.split(key, 3)
        B, L, H, D = 2, 32, 4, 8
        q = jax.random.normal(kq, (B, L, H, D))
        k = jax.random.normal(kk, (B, L, H, D))
        v = jax.random.normal(kv, (B, L, H, D))
        expect = naive_causal_attention(q, k, v)
        got = jax.jit(functools.partial(
            ring_attention_sharded, mesh=sp_mesh))(q, k, v)
        np.testing.assert_allclose(np.asarray(got), np.asarray(expect),
                                   rtol=2e-4, atol=2e-4)

    def test_grads_flow(self, sp_mesh):
        """Ring-attention grads must match the naive reference (not just be
        finite) — guards the ppermute transpose path."""
        B, L, H, D = 1, 16, 2, 4
        x = jax.random.normal(jax.random.PRNGKey(1), (B, L, H, D))

        def loss(q):
            return ring_attention_sharded(q, x, x, mesh=sp_mesh).sum()

        def loss_ref(q):
            return naive_causal_attention(q, x, x).sum()

        g = jax.jit(jax.grad(loss))(x)
        g_ref = jax.jit(jax.grad(loss_ref))(x)
        np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref),
                                   rtol=2e-4, atol=2e-4)


class TestUlysses:
    def test_matches_naive(self, sp_mesh):
        key = jax.random.PRNGKey(2)
        kq, kk, kv = jax.random.split(key, 3)
        B, L, H, D = 2, 32, 8, 4  # H divisible by sp(4) within each tp shard? H local to tp: 8/2=4, sp=4 → 1 head/shard
        q = jax.random.normal(kq, (B, L, H, D))
        k = jax.random.normal(kk, (B, L, H, D))
        v = jax.random.normal(kv, (B, L, H, D))
        expect = naive_causal_attention(q, k, v)
        got = jax.jit(functools.partial(
            ulysses_attention_sharded, mesh=sp_mesh))(q, k, v)
        np.testing.assert_allclose(np.asarray(got), np.asarray(expect),
                                   rtol=2e-4, atol=2e-4)

    def test_non_causal_matches_naive(self, sp_mesh):
        """Bidirectional path (encoders / prefix-LM): full softmax over
        the regathered sequence, no mask."""
        key = jax.random.PRNGKey(5)
        kq, kk, kv = jax.random.split(key, 3)
        B, L, H, D = 2, 32, 8, 4
        q = jax.random.normal(kq, (B, L, H, D))
        k = jax.random.normal(kk, (B, L, H, D))
        v = jax.random.normal(kv, (B, L, H, D))
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * D ** -0.5
        p = jax.nn.softmax(s, axis=-1)
        expect = jnp.einsum("bhqk,bkhd->bqhd", p, v)
        got = jax.jit(functools.partial(
            ulysses_attention_sharded, mesh=sp_mesh, causal=False))(q, k, v)
        np.testing.assert_allclose(np.asarray(got), np.asarray(expect),
                                   rtol=2e-4, atol=2e-4)


class TestPipeline:
    def test_matches_sequential(self):
        devices = np.asarray(jax.devices()[:4]).reshape(4)
        mesh = Mesh(devices, ("pp",))
        n_stages, n_micro, B, F = 4, 6, 3, 5
        key = jax.random.PRNGKey(3)
        w = jax.random.normal(key, (n_stages, F, F)) * 0.3
        xs = jax.random.normal(jax.random.PRNGKey(4), (n_micro, B, F))

        def stage_fn(wi, x):
            return jnp.tanh(x @ wi)

        def run(w, xs):
            return pipeline_apply(
                lambda p, x: stage_fn(p[0], x), w, xs, axis_name="pp")

        got = jax.jit(shard_map_compat(
            run, mesh=mesh, in_specs=(P("pp"), P()), out_specs=P()))(w, xs)

        expect = xs
        for i in range(n_stages):
            expect = jax.vmap(lambda x, wi=w[i]: stage_fn(wi, x))(expect)
        np.testing.assert_allclose(np.asarray(got), np.asarray(expect),
                                   rtol=1e-5, atol=1e-5)

    def test_grad_through_pipeline(self):
        devices = np.asarray(jax.devices()[:4]).reshape(4)
        mesh = Mesh(devices, ("pp",))
        w = jax.random.normal(jax.random.PRNGKey(5), (4, 4, 4)) * 0.2
        xs = jax.random.normal(jax.random.PRNGKey(6), (4, 2, 4))

        def loss(w):
            def run(w, xs):
                out = pipeline_apply(
                    lambda p, x: jnp.tanh(x @ p[0]), w, xs, axis_name="pp")
                return out
            out = shard_map_compat(run, mesh=mesh, in_specs=(P("pp"), P()),
                                   out_specs=P())(w, xs)
            return (out ** 2).sum()

        g = jax.jit(jax.grad(loss))(w)
        assert np.isfinite(np.asarray(g)).all()
        assert float(jnp.abs(g).sum()) > 0


class TestCollectives:
    def test_broadcast_and_allreduce(self):
        devices = np.asarray(jax.devices()).reshape(8)
        mesh = Mesh(devices, ("x",))
        vals = jnp.arange(8.0)

        def f(v):
            b = collectives.broadcast(v, "x", root=3)
            s = collectives.allreduce(v, "x")
            return b, s

        b, s = jax.jit(shard_map_compat(f, mesh=mesh, in_specs=P("x"),
                                        out_specs=P("x")))(vals)
        assert np.allclose(np.asarray(b), 3.0)
        assert np.allclose(np.asarray(s), 28.0)


class TestHybridMesh:
    """ICI x DCN multi-slice meshes (MeshSpec.dcn_dp/dcn_pp): slice-local
    tp/sp/fsdp, DCN-major dp/pp, numeric parity with the flat layout."""

    def test_resolve_fill_per_slice(self):
        s = MeshSpec(dcn_dp=2, tp=2, sp=-1).resolve(8)
        assert s.sp == 2 and s.num_slices == 2 and s.size == 8

    def test_resolve_slice_divisibility(self):
        with pytest.raises(ValueError, match="slices"):
            MeshSpec(dcn_dp=3).resolve(8)

    def test_mesh_axes_merge_dcn_major(self):
        mesh = build_mesh(MeshSpec(dcn_dp=2, sp=2, tp=2))
        assert mesh.shape == {"pp": 1, "dp": 2, "fsdp": 1, "sp": 2, "tp": 2}

    def test_slice_locality(self):
        """Every tp/sp/fsdp neighbour lives in the same slice; dp crosses
        slices only between blocks."""
        mesh = build_mesh(MeshSpec(dcn_dp=2, sp=2, tp=2))
        devs = np.asarray(jax.devices()[:8])
        slice_sets = [set(d.id for d in devs[:4]),
                      set(d.id for d in devs[4:])]
        arr = mesh.devices  # [pp, dp, fsdp, sp, tp]
        for b in range(2):  # dp index == slice index (dcn-major)
            block_ids = {d.id for d in arr[:, b].flatten()}
            assert block_ids == slice_sets[b], (b, block_ids)

    def test_dcn_pp_outer_pipeline(self):
        mesh = build_mesh(MeshSpec(dcn_pp=2, pp=1, sp=2, tp=2))
        assert mesh.shape["pp"] == 2
        arr = mesh.devices
        ids0 = {d.id for d in arr[0].flatten()}
        ids1 = {d.id for d in arr[1].flatten()}
        assert ids0 == {d.id for d in np.asarray(jax.devices()[:4])}
        assert ids1 == {d.id for d in np.asarray(jax.devices()[4:8])}

    def test_numeric_parity_with_flat_mesh(self):
        """Same partition semantics, different device layout: the hybrid
        mesh must train to the same loss as the flat mesh."""
        import optax

        from ray_tpu.models import llama
        from ray_tpu.train.train_step import (make_train_step, shard_batch,
                                              shard_params)

        cfg = llama.LlamaConfig.tiny(n_layers=2, n_heads=8, n_kv_heads=4,
                                     attention="ring")
        tokens = np.random.default_rng(0).integers(
            0, cfg.vocab_size, (4, 64)).astype(np.int32)

        def run(spec):
            mesh = build_mesh(spec)
            params = llama.init_params(cfg, jax.random.PRNGKey(5))
            with mesh:
                params = shard_params(params, mesh, llama.param_specs(cfg))
                init_fn, step_fn = make_train_step(
                    functools.partial(llama.loss_fn, cfg=cfg, mesh=mesh),
                    optax.sgd(1e-2))
                opt_state = init_fn(params)
                batch = shard_batch(jnp.asarray(tokens), mesh)
                for _ in range(2):
                    params, opt_state, m = step_fn(params, opt_state, batch)
            return float(m["loss"])

        flat = run(MeshSpec(dp=2, sp=2, tp=2))
        hybrid = run(MeshSpec(dcn_dp=2, sp=2, tp=2))
        assert hybrid == pytest.approx(flat, rel=1e-5), (flat, hybrid)


class TestRingAttentionFused:
    """The fused inner kernel (Pallas flash block per rotation, interpret
    mode on CPU): forward parity with the naive reference and with the
    einsum ring path, gradient parity through the lse merge."""

    def test_fused_matches_naive(self, sp_mesh):
        key = jax.random.PRNGKey(0)
        kq, kk, kv = jax.random.split(key, 3)
        B, L, H, D = 2, 32, 4, 8
        q = jax.random.normal(kq, (B, L, H, D))
        k = jax.random.normal(kk, (B, L, H, D))
        v = jax.random.normal(kv, (B, L, H, D))
        expect = naive_causal_attention(q, k, v)
        got = jax.jit(functools.partial(
            ring_attention_sharded, mesh=sp_mesh, use_kernel=True,
            interpret=True))(q, k, v)
        np.testing.assert_allclose(np.asarray(got), np.asarray(expect),
                                   rtol=2e-4, atol=2e-4)

    def test_fused_non_causal(self, sp_mesh):
        B, L, H, D = 1, 16, 2, 8
        q = jax.random.normal(jax.random.PRNGKey(3), (B, L, H, D))
        full = jax.jit(functools.partial(
            ring_attention_sharded, mesh=sp_mesh, causal=False))(q, q, q)
        fused = jax.jit(functools.partial(
            ring_attention_sharded, mesh=sp_mesh, causal=False,
            use_kernel=True, interpret=True))(q, q, q)
        np.testing.assert_allclose(np.asarray(fused), np.asarray(full),
                                   rtol=2e-4, atol=2e-4)

    def test_fallback_is_surfaced(self, sp_mesh, monkeypatch):
        """Shard shapes that can't divide into flash blocks surface the
        einsum fallback (VERDICT r4 weak #5): a warning in auto mode, an
        error under RTPU_RING_ATTENTION_STRICT, and last_ring_path()
        records which program actually traced."""
        import warnings as _w

        import importlib

        fa = importlib.import_module("ray_tpu.ops.flash_attention")
        ra = importlib.import_module("ray_tpu.parallel.ring_attention")

        # pretend the kernels lower (CPU test host): the fallback is then
        # a genuine degradation, not the expected portable path
        monkeypatch.setattr(fa, "kernels_supported", lambda *a: True)
        B, L, H, D = 1, 40, 2, 8   # 20 per shard: no divisor >= 8
        q = jax.random.normal(jax.random.PRNGKey(0), (B, L, H, D))
        with _w.catch_warnings(record=True) as got:
            _w.simplefilter("always")
            out = ring_attention_sharded(q, q, q, mesh=sp_mesh)
        assert any(issubclass(w.category, ra.RingAttentionFallbackWarning)
                   for w in got), [str(w.message) for w in got]
        assert ra.last_ring_path() == "einsum"
        # numerics still correct through the fallback
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(naive_causal_attention(q, q, q)),
            rtol=2e-4, atol=2e-4)
        # strict mode refuses to degrade silently
        monkeypatch.setenv("RTPU_RING_ATTENTION_STRICT", "1")
        with pytest.raises(Exception, match="einsum path"):
            ring_attention_sharded(q, q, q, mesh=sp_mesh)
        # divisible shapes on this (CPU) host trace the einsum path with
        # no warning once the kernel pretence is gone
        monkeypatch.setenv("RTPU_RING_ATTENTION_STRICT", "0")
        monkeypatch.setattr(fa, "kernels_supported", lambda *a: False)
        q2 = jax.random.normal(jax.random.PRNGKey(1), (B, 32, H, D))
        with _w.catch_warnings(record=True) as got2:
            _w.simplefilter("always")
            ring_attention_sharded(q2, q2, q2, mesh=sp_mesh)
        assert not any(
            issubclass(w.category, ra.RingAttentionFallbackWarning)
            for w in got2)

    def test_fused_grads_match_naive(self, sp_mesh):
        """Gradient flows through the Pallas backward kernels AND the lse
        merge (whose cotangent folds into delta)."""
        B, L, H, D = 1, 16, 2, 8
        x = jax.random.normal(jax.random.PRNGKey(1), (B, L, H, D))
        w = jax.random.normal(jax.random.PRNGKey(2), (B, L, H, D))

        def loss(q, k, v):
            out = ring_attention_sharded(q, k, v, mesh=sp_mesh,
                                         use_kernel=True, interpret=True)
            return (out * w).sum()

        def loss_ref(q, k, v):
            return (naive_causal_attention(q, k, v) * w).sum()

        g = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))(x, x + 0.1, x - 0.2)
        g_ref = jax.jit(jax.grad(loss_ref, argnums=(0, 1, 2)))(
            x, x + 0.1, x - 0.2)
        for a, b in zip(g, g_ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=5e-4, atol=5e-4)

    def test_compiled_floor_degrades_to_einsum(self, sp_mesh, monkeypatch):
        """ISSUE 7 regression: per-shard length below the Mosaic >= 8
        sublane floor must NEVER pick a compiled block (the old
        min_block=1 call handed Pallas an illegal 4-row block); the
        request degrades to einsum with the fallback warning. The same
        shape in interpret mode (no Mosaic tiling) still runs fused."""
        import warnings as _w

        import importlib

        fa = importlib.import_module("ray_tpu.ops.flash_attention")
        ra = importlib.import_module("ray_tpu.parallel.ring_attention")
        monkeypatch.setattr(fa, "kernels_supported", lambda *a: True)
        B, L, H, D = 1, 16, 2, 8   # 4 per sp=4 shard: below the floor
        q = jax.random.normal(jax.random.PRNGKey(5), (B, L, H, D))
        with _w.catch_warnings(record=True) as got:
            _w.simplefilter("always")
            out = ring_attention_sharded(q, q, q, mesh=sp_mesh,
                                         use_kernel=True)
        assert ra.last_ring_path() == "einsum"
        assert any(issubclass(w.category, ra.RingAttentionFallbackWarning)
                   for w in got)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(naive_causal_attention(q, q, q)),
            rtol=2e-4, atol=2e-4)
        # interpret mode has no sublane floor: the same shard length
        # traces the fused program
        ring_attention_sharded(q, q, q, mesh=sp_mesh,
                               use_kernel=True, interpret=True)
        assert ra.last_ring_path() == "fused"
