"""Per-task/actor runtime environments (reference:
python/ray/_private/runtime_env/ — env_vars + working_dir scope;
unsupported keys fail fast instead of being silently dropped)."""

import os

import pytest

import ray_tpu as rt
from ray_tpu.runtime import runtime_env as rtenv


# ------------------------------------------------------------- validation


def test_unsupported_keys_raise():
    with pytest.raises(NotImplementedError):
        rtenv.validate({"pip": ["requests"]})
    with pytest.raises(NotImplementedError):
        rtenv.validate({"conda": "env.yml"})
    with pytest.raises(ValueError):
        rtenv.validate({"env_vars": {"A": 1}})  # non-str value
    assert rtenv.validate(None) is None
    assert rtenv.validate({}) is None
    assert rtenv.validate({"env_vars": {"A": "1"}}) == {"env_vars": {"A": "1"}}


def test_decorator_rejects_unsupported_env():
    with pytest.raises(NotImplementedError):
        @rt.remote(runtime_env={"pip": ["x"]})
        def f():
            return 1


def test_packaging_deterministic(tmp_path):
    d = tmp_path / "wd"
    d.mkdir()
    (d / "a.txt").write_text("hello")
    (d / "sub").mkdir()
    (d / "sub" / "b.txt").write_text("world")
    uri1, blob1 = rtenv.package_working_dir(str(d))
    uri2, blob2 = rtenv.package_working_dir(str(d))
    assert uri1 == uri2 and blob1 == blob2
    (d / "a.txt").write_text("changed")
    uri3, _ = rtenv.package_working_dir(str(d))
    assert uri3 != uri1


# -------------------------------------------------------------- local mode


def test_local_mode_env_vars(rtpu_local):
    @rtpu_local.remote(runtime_env={"env_vars": {"LOCAL_ENV_X": "on"}})
    def read():
        return os.environ.get("LOCAL_ENV_X")

    assert rtpu_local.get(read.remote(), timeout=30) == "on"
    assert os.environ.get("LOCAL_ENV_X") is None  # restored after the call


def test_local_mode_working_dir_rejected(rtpu_local, tmp_path):
    @rtpu_local.remote(runtime_env={"working_dir": str(tmp_path)})
    def f():
        return 1

    with pytest.raises(Exception):
        rtpu_local.get(f.remote(), timeout=30)


# ------------------------------------------------------------ cluster mode


@pytest.fixture(scope="module")
def env_rt():
    rt.init(num_cpus=4, _system_config={
        "object_store_memory_bytes": 64 * 1024 * 1024,
        "worker_pool_max": 8,
    })
    yield rt
    rt.shutdown()


def test_task_sees_env_vars(env_rt):
    @rt.remote(runtime_env={"env_vars": {"RTPU_TEST_FLAG": "42"}})
    def read():
        return os.environ.get("RTPU_TEST_FLAG")

    @rt.remote
    def read_plain():
        return os.environ.get("RTPU_TEST_FLAG")

    assert rt.get(read.remote(), timeout=60) == "42"
    # a default-environment worker must NOT inherit the env
    assert rt.get(read_plain.remote(), timeout=60) is None


def test_distinct_envs_get_distinct_workers(env_rt):
    @rt.remote(runtime_env={"env_vars": {"WHO": "alpha"}})
    def who_a():
        return os.environ["WHO"], os.getpid()

    @rt.remote(runtime_env={"env_vars": {"WHO": "beta"}})
    def who_b():
        return os.environ["WHO"], os.getpid()

    (va, pa), (vb, pb) = rt.get([who_a.remote(), who_b.remote()], timeout=60)
    assert va == "alpha" and vb == "beta"
    assert pa != pb


def test_working_dir_ships_files_and_modules(env_rt, tmp_path):
    wd = tmp_path / "proj"
    wd.mkdir()
    (wd / "data.txt").write_text("payload-123")
    (wd / "helper_mod_rtenv.py").write_text(
        "def magic():\n    return 777\n")

    @rt.remote(runtime_env={"working_dir": str(wd)})
    def use():
        import helper_mod_rtenv
        with open("data.txt") as f:
            data = f.read()
        return data, helper_mod_rtenv.magic(), os.getcwd()

    data, magic, cwd = rt.get(use.remote(), timeout=90)
    assert data == "payload-123"
    assert magic == 777
    assert str(wd) not in cwd  # ran from the node cache, not the source dir


def test_actor_runtime_env(env_rt):
    @rt.remote(runtime_env={"env_vars": {"ACTOR_ENV": "yes"}})
    class E:
        def read(self):
            return os.environ.get("ACTOR_ENV")

    e = E.remote()
    assert rt.get(e.read.remote(), timeout=60) == "yes"


