"""Expert-parallel MoE tests: sharded all_to_all dispatch must match the
dense reference exactly when capacity covers all routed tokens, grads must
flow, and capacity drops must degrade gracefully (SURVEY §2.6 EP row)."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.parallel.mesh import MeshSpec
from ray_tpu.parallel.moe import (init_moe_params, moe_ffn,
                                  moe_ffn_sharded)


@pytest.fixture(scope="module")
def ep_mesh():
    import numpy as np
    from jax.sharding import Mesh
    devices = np.asarray(jax.devices()[:4])
    return Mesh(devices.reshape(4), ("ep",))


@pytest.fixture(scope="module")
def params():
    return init_moe_params(jax.random.PRNGKey(0), dim=16, ffn_dim=32,
                           num_experts=8)


def test_dense_reference_weights_sum(params):
    x = jax.random.normal(jax.random.PRNGKey(1), (12, 16))
    out = moe_ffn(params, x, top_k=2)
    assert out.shape == x.shape
    assert np.isfinite(np.asarray(out)).all()


def test_sharded_matches_dense(ep_mesh, params):
    x = jax.random.normal(jax.random.PRNGKey(2), (64, 16))
    want = moe_ffn(params, x, top_k=2)
    got = jax.jit(functools.partial(
        moe_ffn_sharded, mesh=ep_mesh, top_k=2,
        capacity_factor=8.0))(params, x)  # capacity >> load: no drops
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-4)


def test_sharded_grads_match_dense(ep_mesh, params):
    x = jax.random.normal(jax.random.PRNGKey(3), (32, 16))

    def loss_sharded(p):
        return (moe_ffn_sharded(p, x, mesh=ep_mesh,
                                capacity_factor=8.0) ** 2).sum()

    def loss_dense(p):
        return (moe_ffn(p, x) ** 2).sum()

    g = jax.jit(jax.grad(loss_sharded))(params)
    g_ref = jax.jit(jax.grad(loss_dense))(params)
    for k in params:
        np.testing.assert_allclose(np.asarray(g[k]), np.asarray(g_ref[k]),
                                   rtol=2e-3, atol=2e-3), k


def test_capacity_drops_are_bounded(ep_mesh, params):
    """With a tight capacity the output degrades (dropped tokens emit 0
    residual) but never produces non-finite values."""
    x = jax.random.normal(jax.random.PRNGKey(4), (64, 16))
    got = jax.jit(functools.partial(
        moe_ffn_sharded, mesh=ep_mesh, top_k=2,
        capacity_factor=0.5))(params, x)
    assert np.isfinite(np.asarray(got)).all()
