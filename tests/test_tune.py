"""ray_tpu.tune tests.

Coverage model mirrors the reference's tune tests (reference:
python/ray/tune/tests/test_tune_controller.py, test_trial_scheduler.py,
test_trial_scheduler_pbt.py scope): variant generation, FIFO runs,
ASHA early stopping, PBT exploit/explore beating fixed hyperparams,
failure retry, and experiment restore.
"""

import math

import pytest

import ray_tpu as rt
from ray_tpu import tune


@pytest.fixture(scope="module")
def local_rt():
    rt.init(local_mode=True, num_cpus=8)
    yield rt
    rt.shutdown()


# ------------------------------------------------------------ search spaces


def test_generate_variants_grid_and_random():
    from ray_tpu.tune.search import generate_variants
    space = {
        "a": tune.grid_search([1, 2, 3]),
        "b": tune.uniform(0.0, 1.0),
        "c": "const",
    }
    variants = generate_variants(space, num_samples=2, seed=0)
    assert len(variants) == 6  # 3 grid points x 2 samples
    assert all(v["c"] == "const" for v in variants)
    assert all(0.0 <= v["b"] <= 1.0 for v in variants)
    assert sorted({v["a"] for v in variants}) == [1, 2, 3]


def test_domains_sample_ranges():
    import random
    rng = random.Random(0)
    assert 1 <= tune.randint(1, 10).sample(rng) < 10
    lo = tune.loguniform(1e-4, 1e-1)
    for _ in range(20):
        assert 1e-4 <= lo.sample(rng) <= 1e-1
    assert tune.choice(["x", "y"]).sample(rng) in ("x", "y")


# ------------------------------------------------------------------- basics


def test_fifo_runs_all_trials(local_rt):
    def trainable(cfg):
        for _ in range(3):
            tune.report({"score": cfg["x"] * 2})

    tuner = tune.Tuner(
        trainable,
        param_space={"x": tune.grid_search([1, 2, 3, 4])},
        tune_config=tune.TuneConfig(metric="score", mode="max"))
    grid = tuner.fit()
    assert len(grid.trials) == 4
    assert all(t.status == tune.TrialStatus.TERMINATED for t in grid.trials)
    best = grid.get_best_result()
    assert best.config["x"] == 4 and best.last_result["score"] == 8
    rows = grid.get_dataframe()
    assert len(rows) == 4 and all("config/x" in r for r in rows)


def test_trial_error_surfaces_and_retries(local_rt):
    calls = {"n": 0}

    def flaky(cfg):
        tune.report({"score": 1})
        raise RuntimeError("trial-boom")

    tuner = tune.Tuner(
        flaky, param_space={"x": 1},
        tune_config=tune.TuneConfig(metric="score", mode="max"))
    grid = tuner.fit()
    assert len(grid.errors) == 1
    assert "trial-boom" in grid.trials[0].error


# --------------------------------------------------------------------- ASHA


def test_asha_stops_bad_trials_early(local_rt):
    MAX_T = 32

    def trainable(cfg):
        for i in range(MAX_T):
            tune.report({"score": cfg["slope"] * (i + 1)})

    # Strong trials first: rung cutoffs are populated by good scores, so
    # weak late arrivals fall below the top-1/rf quantile and stop (with
    # ascending order ASHA would legitimately keep everything — each new
    # arrival would be the best seen so far).
    tuner = tune.Tuner(
        trainable,
        param_space={"slope": tune.grid_search(
            [4.0, 3.0, 2.0, 1.0, 0.4, 0.3, 0.2, 0.1])},
        tune_config=tune.TuneConfig(
            metric="score", mode="max",
            scheduler=tune.ASHAScheduler(
                max_t=MAX_T, grace_period=2, reduction_factor=2),
            max_concurrent_trials=2))
    grid = tuner.fit()
    iters = {t.config["slope"]: t.iteration for t in grid.trials}
    total = sum(iters.values())
    assert total < 8 * MAX_T * 0.8, f"ASHA saved no work: {iters}"
    # the best trial must have survived to (near) the end
    assert iters[4.0] >= MAX_T - 1, iters
    best = grid.get_best_result()
    assert best.config["slope"] == 4.0


# ---------------------------------------------------------------------- PBT


def test_pbt_exploit_beats_stuck_trials(local_rt):
    """Half the population starts with a divergent lr on a quadratic bowl;
    PBT must clone the good trials' (x, lr) into the bad ones so EVERY
    trial converges — without exploit the lr=1.99 trials oscillate forever
    (reference done-criterion: PBT beats fixed hyperparams)."""
    STEPS = 24

    def trainable(cfg):
        state = tune.get_checkpoint()
        x = state["x"] if state else 5.0
        lr = cfg["lr"]
        start = state["step"] if state else 0
        for step in range(start, STEPS):
            x = x - lr * 2 * x  # GD on f(x) = x^2
            tune.report({"loss": x * x},
                        checkpoint={"x": x, "step": step + 1})

    tuner = tune.Tuner(
        trainable,
        param_space={"lr": tune.grid_search([0.3, 0.3, 1.99, 1.99])},
        tune_config=tune.TuneConfig(
            metric="loss", mode="min",
            scheduler=tune.PopulationBasedTraining(
                perturbation_interval=4,
                hyperparam_mutations={"lr": tune.uniform(0.1, 0.5)},
                quantile_fraction=0.5,
                seed=0),
            max_concurrent_trials=4))
    grid = tuner.fit()
    losses = sorted(t.last_result["loss"] for t in grid.trials)
    # fixed lr=1.99 ends with loss ~ (0.98^24 * 5)^2 ≈ 15; exploited trials
    # must have copied a converging state instead
    assert losses[-1] < 1.0, f"PBT failed to rescue stuck trials: {losses}"


# ------------------------------------------------------------------ restore


def test_experiment_restore_resumes(local_rt, tmp_path):
    def trainable(cfg):
        state = tune.get_checkpoint()
        start = state["step"] if state else 0
        if start == 0 and cfg["x"] == 2:
            # first run of trial x=2 dies midway
            tune.report({"score": 0}, checkpoint={"step": 1})
            raise RuntimeError("mid-crash")
        for step in range(start, 3):
            tune.report({"score": cfg["x"] * 10 + step},
                        checkpoint={"step": step + 1})

    tuner = tune.Tuner(
        trainable,
        param_space={"x": tune.grid_search([1, 2])},
        tune_config=tune.TuneConfig(metric="score", mode="max"),
        run_config=tune.TuneRunConfig(storage_path=str(tmp_path),
                                      name="exp1"))
    grid = tuner.fit()
    assert len(grid.errors) == 1
    storage = grid.storage_path

    restored = tune.Tuner.restore(storage, trainable)
    grid2 = restored.fit()
    assert not grid2.errors
    by_x = {t.config["x"]: t for t in grid2.trials}
    # trial x=2 resumed from its step-1 checkpoint and finished
    assert by_x[2].last_result["score"] == 22
    assert by_x[2].status == tune.TrialStatus.TERMINATED
    # finished trial x=1 kept its result without re-running
    assert by_x[1].last_result["score"] == 12


# --------------------------------------------------------------- searchers


def test_basic_variant_searcher_matches_generator(local_rt):
    """The Searcher seam serves grid/random variants identically to the
    direct path (reference: BasicVariantGenerator through searcher.py)."""
    space = {"a": tune.grid_search([1, 2]), "b": tune.uniform(0, 1)}

    def trainable(config):
        tune.report({"loss": config["a"] + config["b"]})

    results = tune.Tuner(
        trainable, param_space=space,
        tune_config=tune.TuneConfig(
            metric="loss", mode="min", num_samples=2,
            search_alg=tune.BasicVariantSearcher(num_samples=2, seed=0)),
    ).fit()
    assert len(results.trials) == 4  # 2 grid x 2 samples
    assert not results.errors


def test_sequential_searcher_feedback_improves(local_rt):
    """A model-based searcher sees earlier waves' results and concentrates
    later suggestions near the optimum (the seam the reference's
    Optuna/HyperOpt plugins rely on)."""
    space = {"x": tune.uniform(-5.0, 5.0)}

    def trainable(config):
        tune.report({"loss": (config["x"] - 2.0) ** 2})

    searcher = tune.HyperOptLikeSearcher(num_samples=24, warmup=6,
                                         seed=7)
    results = tune.Tuner(
        trainable, param_space=space,
        tune_config=tune.TuneConfig(
            metric="loss", mode="min", search_alg=searcher,
            max_concurrent_trials=6),
    ).fit()
    assert len(results.trials) == 24
    best = results.get_best_result()
    assert abs(best.config["x"] - 2.0) < 1.0, best.config
    # feedback actually flowed: the searcher recorded observations
    assert len(searcher._observed) == 24
    # later waves should cluster nearer the optimum than the warmup
    first_wave = [abs(c["x"] - 2.0) for _, c in searcher._observed[:6]]
    last_wave = [abs(c["x"] - 2.0) for _, c in searcher._observed[-6:]]
    assert sum(last_wave) / 6 <= sum(first_wave) / 6 + 0.5


def test_median_stopping_rule_prunes_below_median(local_rt):
    MAX_T = 24

    def trainable(cfg):
        for i in range(MAX_T):
            tune.report({"score": cfg["slope"] * (i + 1)})

    # strong trials first so the per-step median is already meaningful
    # when the weak trials arrive (same rationale as the ASHA test)
    tuner = tune.Tuner(
        trainable,
        param_space={"slope": tune.grid_search(
            [4.0, 3.0, 2.0, 1.0, 0.4, 0.3, 0.2, 0.1])},
        tune_config=tune.TuneConfig(
            metric="score", mode="max",
            scheduler=tune.MedianStoppingRule(
                grace_period=2, min_samples_required=2),
            max_concurrent_trials=2))
    grid = tuner.fit()
    iters = {t.config["slope"]: t.iteration for t in grid.trials}
    total = sum(iters.values())
    assert total < 8 * MAX_T * 0.8, f"median rule saved no work: {iters}"
    # the best trial must run to completion; the worst must stop early
    assert iters[4.0] >= MAX_T - 1, iters
    assert iters[0.1] < MAX_T, iters
    assert grid.get_best_result().config["slope"] == 4.0
