"""LLM inference tests: paged attention numerics, engine-vs-oracle greedy
decoding, continuous batching invariance, page recycling.

The reference has no in-tree equivalent (vLLM does this on GPU); the
oracle here is the training-path Llama forward (models/llama.py) run
autoregressively on the full sequence each step — the engine's paged
incremental path must reproduce its greedy choices exactly.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.llm import InferenceEngine
from ray_tpu.llm.cache import PageAllocator
from ray_tpu.models.llama import LlamaConfig, forward, init_params
from ray_tpu.ops.paged_attention import (_paged_attention_pallas,
                                         paged_attention_reference)

CFG = LlamaConfig.tiny(n_layers=2, dtype=jnp.float32)


@pytest.fixture(scope="module")
def params():
    return init_params(CFG, jax.random.PRNGKey(7))


# ------------------------------------------------------------------ kernel


def test_paged_attention_reference_matches_dense():
    key = jax.random.PRNGKey(0)
    B, Hq, Hkv, D, ps, P = 2, 8, 4, 64, 8, 10
    ks = jax.random.split(key, 3)
    q = jax.random.normal(ks[0], (B, Hq, D))
    kp = jax.random.normal(ks[1], (P, Hkv, ps, D))
    vp = jax.random.normal(ks[2], (P, Hkv, ps, D))
    pt = jnp.array([[1, 2, 3], [4, 5, 6]], jnp.int32)
    sl = jnp.array([11, 24], jnp.int32)
    out = paged_attention_reference(q, kp, vp, pt, sl)
    for b in range(B):
        k = kp[pt[b]].transpose(1, 0, 2, 3).reshape(Hkv, -1, D)[:, :sl[b]]
        v = vp[pt[b]].transpose(1, 0, 2, 3).reshape(Hkv, -1, D)[:, :sl[b]]
        qg = q[b].reshape(Hkv, Hq // Hkv, D)
        s = jnp.einsum("gqd,gtd->gqt", qg, k) * D ** -0.5
        o = jnp.einsum("gqt,gtd->gqd",
                       jax.nn.softmax(s, -1), v).reshape(Hq, D)
        np.testing.assert_allclose(out[b], o, atol=1e-5)


def test_paged_attention_pallas_interpret_matches_reference():
    key = jax.random.PRNGKey(3)
    B, Hq, Hkv, D, ps, P = 3, 8, 4, 128, 16, 12
    ks = jax.random.split(key, 3)
    q = jax.random.normal(ks[0], (B, Hq, D), jnp.float32)
    kp = jax.random.normal(ks[1], (P, Hkv, ps, D), jnp.float32)
    vp = jax.random.normal(ks[2], (P, Hkv, ps, D), jnp.float32)
    pt = jnp.array([[0, 1, 2], [3, 4, 5], [6, 7, 8]], jnp.int32)
    sl = jnp.array([5, 33, 48], jnp.int32)
    ref = paged_attention_reference(q, kp, vp, pt, sl)
    out = _paged_attention_pallas(q, kp, vp, pt, sl, D ** -0.5,
                                  interpret=True)
    # tolerance covers MXU-emulation dot precision, not logic
    np.testing.assert_allclose(out, ref, atol=2e-2)


# ------------------------------------------------------------------ engine


def _oracle_greedy(params, prompt, n_tokens):
    """Autoregressive greedy decode via the full training forward."""
    toks = list(prompt)
    for _ in range(n_tokens):
        logits = forward(params, jnp.asarray([toks]), CFG)
        toks.append(int(jnp.argmax(logits[0, -1])))
    return toks[len(prompt):]


def test_engine_matches_full_forward_greedy(params):
    eng = InferenceEngine(CFG, params, page_size=8, total_pages=64,
                          max_batch=4, max_seq_len=128)
    prompt = [5, 17, 42, 9, 100, 3, 77]
    got = eng.generate(prompt, max_new_tokens=12)
    want = _oracle_greedy(params, prompt, 12)
    assert got == want, f"paged decode diverged: {got} vs {want}"


def test_engine_prompt_padding_invariance(params):
    # prompt lengths around the bucket/page boundaries must not matter
    eng = InferenceEngine(CFG, params, page_size=8, total_pages=64,
                          max_batch=4, max_seq_len=128)
    for plen in (1, 7, 8, 9, 16, 17):
        prompt = [(3 * i + 1) % CFG.vocab_size for i in range(plen)]
        got = eng.generate(prompt, max_new_tokens=6)
        want = _oracle_greedy(params, prompt, 6)
        assert got == want, f"len {plen}: {got} vs {want}"


def test_continuous_batching_invariance(params):
    """Interleaved requests must produce exactly what each produces alone
    (continuous batching must not leak state across slots)."""
    eng = InferenceEngine(CFG, params, page_size=8, total_pages=128,
                          max_batch=4, max_seq_len=128)
    prompts = [[11, 22, 33], [101, 5], [60, 61, 62, 63, 64]]
    solo = [_oracle_greedy(params, p, 8) for p in prompts]
    rids = [eng.add_request(p, 8) for p in prompts]
    results = {}
    for _ in range(200):
        results.update(eng.step())
        if len(results) == len(rids):
            break
    for rid, want in zip(rids, solo):
        assert results[rid] == want, f"{rid}: {results[rid]} vs {want}"
    # batches actually shared decode dispatches (continuous batching +
    # multi-step chunking: far fewer device round-trips than tokens)
    assert eng.stats["decode_dispatches"] < sum(len(s) for s in solo) // 2


def test_eos_stops_and_pages_recycle(params):
    eng = InferenceEngine(CFG, params, page_size=8, total_pages=16,
                          max_batch=2, max_seq_len=64)
    free0 = eng.allocator.num_free
    prompt = [5, 17, 42]
    first = _oracle_greedy(params, prompt, 3)
    eos = first[2]
    # greedy tiny models repeat tokens: expected output is the oracle
    # stream truncated at the FIRST occurrence of eos
    want = first[:first.index(eos)] if eos in first else first
    eng.eos_token = eos
    got = eng.generate(prompt, max_new_tokens=10)
    assert got == want, f"eos not honored: {got} vs {want}"
    assert eng.allocator.num_free == free0, "pages leaked after finish"


def test_page_allocator():
    a = PageAllocator(8)
    assert a.num_free == 7  # page 0 reserved
    got = a.alloc(3)
    assert got is not None and len(got) == 3 and 0 not in got
    assert a.alloc(10) is None
    a.free(got)
    assert a.num_free == 7


def test_batched_prefill_group_matches_oracle(params):
    """Same-bucket prompts admit as ONE batched prefill dispatch and
    still reproduce each prompt's solo greedy output exactly."""
    eng = InferenceEngine(CFG, params, page_size=8, total_pages=128,
                          max_batch=4, max_seq_len=128, prefill_batch=4)
    # all in the 16-bucket (lengths 9..16) -> one group of 3 (padded to 4)
    prompts = [[7 + i for i in range(12)],
               [40 + i for i in range(10)],
               [90 + i for i in range(15)]]
    solo = [_oracle_greedy(params, p, 6) for p in prompts]
    rids = [eng.add_request(p, 6) for p in prompts]
    results = dict(eng.step())   # one step admits the whole group
    assert eng.stats["prefill_dispatches"] == 1, \
        "three same-bucket prompts should ride ONE prefill dispatch"
    for _ in range(100):
        if len(results) == len(rids):
            break
        results.update(eng.step())
    for rid, want in zip(rids, solo):
        assert results[rid] == want, f"{rid}: {results[rid]} vs {want}"


# ------------------------------------------------------------------- tp


def test_tp_engine_matches_single_chip(params):
    """tp=2 sharded engine (weights Megatron-split, kv-heads sharded over
    a ('tp',) mesh) reproduces the tp=1 greedy stream exactly — single
    AND batched prefill paths (reference capability: vllm_models.py
    tensor_parallel_size; here the mesh IS the worker group)."""
    kw = dict(page_size=8, total_pages=64, max_batch=4, max_seq_len=128,
              decode_chunk=4)
    e1 = InferenceEngine(CFG, params, **kw)
    e2 = InferenceEngine(CFG, params, tp=2, **kw)
    assert e2.mesh is not None and e2.mesh.shape["tp"] == 2
    prompt = [5, 17, 42, 9, 100, 3, 77]
    assert e2.generate(prompt, max_new_tokens=10) == \
        e1.generate(prompt, max_new_tokens=10)
    # batched prefill (prefill_many under shard_map) parity
    prompts = [[11, 22, 33], [101, 5, 9], [60, 61, 62, 63, 64]]
    r1 = [e1.add_request(p, 6) for p in prompts]
    r2 = [e2.add_request(p, 6) for p in prompts]
    d1, d2 = {}, {}
    for _ in range(100):
        d1.update(e1.step())
        d2.update(e2.step())
        if len(d1) == len(r1) and len(d2) == len(r2):
            break
    for a, b in zip(r1, r2):
        assert d1[a] == d2[b], (d1[a], d2[b])
    assert e2.stats["prefill_dispatches"] == e1.stats["prefill_dispatches"]


def test_tp_validation():
    from ray_tpu.llm.tp import validate_tp
    with pytest.raises(ValueError):
        validate_tp(CFG, 3)           # 3 does not divide n_kv_heads=4
    with pytest.raises(ValueError):
        InferenceEngine(CFG, tp=64)   # more shards than devices


def test_batched_prefill_mixed_buckets_split(params):
    """A different-bucket prompt at the group boundary waits for the
    next step's group instead of forcing a bigger pad."""
    eng = InferenceEngine(CFG, params, page_size=8, total_pages=128,
                          max_batch=4, max_seq_len=128, prefill_batch=4)
    short = [5, 6, 7]                     # 16-bucket (min bucket is 16)
    long = [20 + i for i in range(20)]    # 32-bucket
    solo = [_oracle_greedy(params, p, 5) for p in (short, long)]
    rids = [eng.add_request(short, 5), eng.add_request(long, 5)]
    results = {}
    for _ in range(100):
        results.update(eng.step())
        if len(results) == 2:
            break
    for rid, want in zip(rids, solo):
        assert results[rid] == want
