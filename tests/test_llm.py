"""LLM inference tests: paged attention numerics, engine-vs-oracle greedy
decoding, continuous batching invariance, page recycling.

The reference has no in-tree equivalent (vLLM does this on GPU); the
oracle here is the training-path Llama forward (models/llama.py) run
autoregressively on the full sequence each step — the engine's paged
incremental path must reproduce its greedy choices exactly.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.llm import InferenceEngine
from ray_tpu.llm.cache import PageAllocator
from ray_tpu.models.llama import LlamaConfig, forward, init_params
from ray_tpu.ops.paged_attention import (_paged_attention_pallas,
                                         paged_attention_reference)

CFG = LlamaConfig.tiny(n_layers=2, dtype=jnp.float32)


@pytest.fixture(scope="module")
def params():
    return init_params(CFG, jax.random.PRNGKey(7))


# ------------------------------------------------------------------ kernel


def test_paged_attention_reference_matches_dense():
    key = jax.random.PRNGKey(0)
    B, Hq, Hkv, D, ps, P = 2, 8, 4, 64, 8, 10
    ks = jax.random.split(key, 3)
    q = jax.random.normal(ks[0], (B, Hq, D))
    kp = jax.random.normal(ks[1], (P, Hkv, ps, D))
    vp = jax.random.normal(ks[2], (P, Hkv, ps, D))
    pt = jnp.array([[1, 2, 3], [4, 5, 6]], jnp.int32)
    sl = jnp.array([11, 24], jnp.int32)
    out = paged_attention_reference(q, kp, vp, pt, sl)
    for b in range(B):
        k = kp[pt[b]].transpose(1, 0, 2, 3).reshape(Hkv, -1, D)[:, :sl[b]]
        v = vp[pt[b]].transpose(1, 0, 2, 3).reshape(Hkv, -1, D)[:, :sl[b]]
        qg = q[b].reshape(Hkv, Hq // Hkv, D)
        s = jnp.einsum("gqd,gtd->gqt", qg, k) * D ** -0.5
        o = jnp.einsum("gqt,gtd->gqd",
                       jax.nn.softmax(s, -1), v).reshape(Hq, D)
        np.testing.assert_allclose(out[b], o, atol=1e-5)


def test_paged_attention_pallas_interpret_matches_reference():
    key = jax.random.PRNGKey(3)
    B, Hq, Hkv, D, ps, P = 3, 8, 4, 128, 16, 12
    ks = jax.random.split(key, 3)
    q = jax.random.normal(ks[0], (B, Hq, D), jnp.float32)
    kp = jax.random.normal(ks[1], (P, Hkv, ps, D), jnp.float32)
    vp = jax.random.normal(ks[2], (P, Hkv, ps, D), jnp.float32)
    pt = jnp.array([[0, 1, 2], [3, 4, 5], [6, 7, 8]], jnp.int32)
    sl = jnp.array([5, 33, 48], jnp.int32)
    ref = paged_attention_reference(q, kp, vp, pt, sl)
    out = _paged_attention_pallas(q, kp, vp, pt, sl, D ** -0.5,
                                  interpret=True)
    # tolerance covers MXU-emulation dot precision, not logic
    np.testing.assert_allclose(out, ref, atol=2e-2)


# ------------------------------------------------------------------ engine


def _oracle_greedy(params, prompt, n_tokens):
    """Autoregressive greedy decode via the full training forward."""
    toks = list(prompt)
    for _ in range(n_tokens):
        logits = forward(params, jnp.asarray([toks]), CFG)
        toks.append(int(jnp.argmax(logits[0, -1])))
    return toks[len(prompt):]


def test_engine_matches_full_forward_greedy(params):
    eng = InferenceEngine(CFG, params, page_size=8, total_pages=64,
                          max_batch=4, max_seq_len=128)
    prompt = [5, 17, 42, 9, 100, 3, 77]
    got = eng.generate(prompt, max_new_tokens=12)
    want = _oracle_greedy(params, prompt, 12)
    assert got == want, f"paged decode diverged: {got} vs {want}"


def test_engine_prompt_padding_invariance(params):
    # prompt lengths around the bucket/page boundaries must not matter
    eng = InferenceEngine(CFG, params, page_size=8, total_pages=64,
                          max_batch=4, max_seq_len=128)
    for plen in (1, 7, 8, 9, 16, 17):
        prompt = [(3 * i + 1) % CFG.vocab_size for i in range(plen)]
        got = eng.generate(prompt, max_new_tokens=6)
        want = _oracle_greedy(params, prompt, 6)
        assert got == want, f"len {plen}: {got} vs {want}"


def test_continuous_batching_invariance(params):
    """Interleaved requests must produce exactly what each produces alone
    (continuous batching must not leak state across slots)."""
    eng = InferenceEngine(CFG, params, page_size=8, total_pages=128,
                          max_batch=4, max_seq_len=128)
    prompts = [[11, 22, 33], [101, 5], [60, 61, 62, 63, 64]]
    solo = [_oracle_greedy(params, p, 8) for p in prompts]
    rids = [eng.add_request(p, 8) for p in prompts]
    results = {}
    for _ in range(200):
        results.update(eng.step())
        if len(results) == len(rids):
            break
    for rid, want in zip(rids, solo):
        assert results[rid] == want, f"{rid}: {results[rid]} vs {want}"
    # batches actually shared decode dispatches (continuous batching +
    # multi-step chunking: far fewer device round-trips than tokens)
    assert eng.stats["decode_dispatches"] < sum(len(s) for s in solo) // 2


def test_eos_stops_and_pages_recycle(params):
    eng = InferenceEngine(CFG, params, page_size=8, total_pages=16,
                          max_batch=2, max_seq_len=64)
    free0 = eng.allocator.num_free
    prompt = [5, 17, 42]
    first = _oracle_greedy(params, prompt, 3)
    eos = first[2]
    # greedy tiny models repeat tokens: expected output is the oracle
    # stream truncated at the FIRST occurrence of eos
    want = first[:first.index(eos)] if eos in first else first
    eng.eos_token = eos
    got = eng.generate(prompt, max_new_tokens=10)
    assert got == want, f"eos not honored: {got} vs {want}"
    assert eng.allocator.num_free == free0, "pages leaked after finish"


def test_page_allocator():
    a = PageAllocator(8)
    assert a.num_free == 7  # page 0 reserved
    got = a.alloc(3)
    assert got is not None and len(got) == 3 and 0 not in got
    assert a.alloc(10) is None
    a.free(got)
    assert a.num_free == 7


def test_page_allocator_refcounts_and_double_free():
    from ray_tpu.llm.cache import DoubleFreeError
    a = PageAllocator(8)            # strict under pytest
    (p,) = a.alloc(1)
    assert a.refcount(p) == 1
    a.incref([p])
    assert a.refcount(p) == 2
    a.free([p])                     # decref only: still allocated
    assert a.refcount(p) == 1 and a.num_free == 6
    a.free([p])                     # last ref: back on the free list
    assert a.refcount(p) == 0 and a.num_free == 7
    with pytest.raises(DoubleFreeError):
        a.free([p])
    with pytest.raises(ValueError):
        a.incref([p])               # unallocated page can't gain sharers
    relaxed = PageAllocator(8, strict_free=False)
    (q,) = relaxed.alloc(1)
    relaxed.free([q])
    relaxed.free([q])               # production mode: logged and skipped
    assert relaxed.num_free == 7


def test_prefix_cache_match_register_evict():
    from ray_tpu.llm.cache import PrefixCache
    a = PageAllocator(16)
    c = PrefixCache(a, page_size=4)
    prompt = list(range(10))        # 2 full blocks + 2-token tail
    pages = a.alloc(3)
    c.register(prompt, pages)       # publishes the 2 full blocks only
    assert c.num_cached == 2
    hit, matched, cow = c.match(prompt)
    assert hit == pages[:2] and matched == 8 and not cow
    # exact page multiple: cap at len-1 cuts into the last shared page
    hit2, matched2, cow2 = c.match(prompt[:8])
    assert matched2 == 7 and cow2
    # different second block: partial (single-block) match
    hit3, matched3, _ = c.match(prompt[:4] + [99, 98, 97, 96])
    assert hit3 == pages[:1] and matched3 == 4
    for h in (hit, hit2, hit3):
        a.free(h)
        c.note_release(h)
    assert c.num_evictable == 0     # original refs still held
    a.free(pages)
    c.note_release(pages)
    assert c.num_evictable == 2     # only the cache references them now
    assert c.evict(5) == 2 and c.num_cached == 0
    assert a.num_free == 15


def test_multi_prompt_single_ragged_dispatch(params):
    """Several waiting prompts admit together and ALL their prefill
    chunks ride ONE ragged step dispatch (the fused argmax hands each
    its first token from the same program) — and each still reproduces
    its solo greedy output exactly."""
    eng = InferenceEngine(CFG, params, page_size=8, total_pages=128,
                          max_batch=4, max_seq_len=128,
                          prefill_chunk=16, prefill_rows=3)
    prompts = [[7 + i for i in range(12)],
               [40 + i for i in range(10)],
               [90 + i for i in range(15)]]
    solo = [_oracle_greedy(params, p, 6) for p in prompts]
    rids = [eng.add_request(p, 6) for p in prompts]
    results = dict(eng.step())   # one step admits + prefills all three
    assert eng.stats["ragged_dispatches"] == 1, \
        "three prompts' prefills should ride ONE ragged dispatch"
    for _ in range(100):
        if len(results) == len(rids):
            break
        results.update(eng.step())
    for rid, want in zip(rids, solo):
        assert results[rid] == want, f"{rid}: {results[rid]} vs {want}"


# ------------------------------------- chunked prefill + prefix caching


def test_chunked_prefill_matches_oracle(params):
    """Chunk-by-chunk prefill (chunk attention over prior paged KV) must
    reproduce the one-shot prefill greedy stream exactly."""
    eng = InferenceEngine(CFG, params, page_size=8, total_pages=64,
                          max_batch=4, max_seq_len=128,
                          prefix_cache=False, prefill_chunk=8)
    prompt = [(5 * i + 2) % CFG.vocab_size for i in range(20)]
    got = eng.generate(prompt, max_new_tokens=8)
    assert eng.stats["ragged_dispatches"] == 3   # 8 + 8 + 4 tokens
    assert got == _oracle_greedy(params, prompt, 8)


def test_step_token_budget_slices_chunks(params):
    """A per-step budget below prefill_chunk bounds each step's chunk;
    the output is budget-invariant."""
    eng = InferenceEngine(CFG, params, page_size=8, total_pages=64,
                          max_batch=4, max_seq_len=128,
                          prefix_cache=False, prefill_chunk=8,
                          step_token_budget=4)
    prompt = [(5 * i + 2) % CFG.vocab_size for i in range(20)]
    got = eng.generate(prompt, max_new_tokens=8)
    assert eng.stats["ragged_dispatches"] == 5   # 4-token slices
    assert got == _oracle_greedy(params, prompt, 8)


def test_prefix_cache_hit_and_cached_tokens(params):
    """A repeated prompt reuses its full KV pages: only the tail
    prefills, the output is unchanged, and cached tokens are reported."""
    eng = InferenceEngine(CFG, params, page_size=8, total_pages=64,
                          max_batch=4, max_seq_len=128)
    prompt = [(7 * i + 3) % CFG.vocab_size for i in range(20)]
    want = _oracle_greedy(params, prompt, 8)
    assert eng.generate(prompt, max_new_tokens=8) == want   # cold
    pf0 = eng.stats["prefill_tokens"]
    rid = eng.add_request(prompt, 8)
    done = {}
    for _ in range(100):
        done.update(eng.step())
        if rid in done:
            break
    assert done[rid] == want
    assert eng.stats["cached_tokens"] == 16     # 2 full pages reused
    assert eng.cached_tokens(rid) == 16
    assert eng.cached_tokens(rid) == 0          # accounting pops
    assert eng.stats["prefill_tokens"] - pf0 == 4   # only the tail


def test_prefix_cache_partial_hit(params):
    """Prompts sharing only the first page reuse exactly that page."""
    eng = InferenceEngine(CFG, params, page_size=8, total_pages=64,
                          max_batch=4, max_seq_len=128)
    a = [(3 * i + 2) % CFG.vocab_size for i in range(20)]
    b = a[:8] + [(11 * i + 5) % CFG.vocab_size for i in range(12)]
    assert eng.generate(a, 6) == _oracle_greedy(params, a, 6)
    want = _oracle_greedy(params, b, 6)
    rid = eng.add_request(b, 6)
    done = {}
    for _ in range(100):
        done.update(eng.step())
        if rid in done:
            break
    assert done[rid] == want
    assert eng.cached_tokens(rid) == 8


def test_prefix_cache_cow_on_exact_page_multiple(params):
    """Prompt length an exact page multiple with every block cached: the
    match caps at len-1, which lands the tail INSIDE the last shared
    page — the engine must copy it (COW) and still match the oracle."""
    eng = InferenceEngine(CFG, params, page_size=8, total_pages=64,
                          max_batch=4, max_seq_len=128)
    prompt = [(9 * i + 4) % CFG.vocab_size for i in range(16)]
    want = _oracle_greedy(params, prompt, 6)
    assert eng.generate(prompt, max_new_tokens=6) == want
    rid = eng.add_request(prompt, 6)
    done = {}
    for _ in range(100):
        done.update(eng.step())
        if rid in done:
            break
    assert done[rid] == want
    assert eng.stats["cow_copies"] == 1
    assert eng.cached_tokens(rid) == 15


def test_prefix_cache_evicts_under_pressure(params):
    """Cached pages are free HBM: when a new prompt can't allocate, LRU
    cached pages return to the free list and admission succeeds."""
    eng = InferenceEngine(CFG, params, page_size=8, total_pages=8,
                          max_batch=2, max_seq_len=64)
    small = [(2 * i + 1) % CFG.vocab_size for i in range(16)]
    assert eng.generate(small, 4) == _oracle_greedy(params, small, 4)
    assert eng.prefix.num_evictable == 2        # its 2 full pages cached
    big = [(13 * i + 7) % CFG.vocab_size for i in range(40)]
    assert eng.generate(big, 4) == _oracle_greedy(params, big, 4)
    assert eng.prefix.evictions >= 1


def test_decode_interleaves_with_chunked_prefill(params):
    """A long prompt chunk-prefills WHILE the running batch keeps
    decoding — the decode stream is never stalled for the whole prefill
    (the head-of-line fix this PR is for)."""
    eng = InferenceEngine(CFG, params, page_size=8, total_pages=128,
                          max_batch=4, max_seq_len=256, decode_chunk=4,
                          prefix_cache=False, prefill_chunk=8,
                          step_token_budget=8)
    a = [9, 4, 33, 2, 71]
    b = [(5 * i + 1) % CFG.vocab_size for i in range(40)]
    wa = _oracle_greedy(params, a, 28)    # 7 decode dispatches of 4:
    wb = _oracle_greedy(params, b, 4)     # outlives b's 5 chunk steps
    results = {}
    ra = eng.add_request(a, 28)
    results.update(eng.step())          # a joins the decode batch
    d0 = eng.stats["decode_tokens"]
    rb = eng.add_request(b, 4)
    for _ in range(20):
        results.update(eng.step())
        if not any(s.request_id == rb for s in eng._chunking):
            break
    # a's prefill rode dispatch 1; b's 40 tokens take 5 more (budget 8)
    assert eng.stats["ragged_dispatches"] == 6
    assert eng.stats["decode_tokens"] > d0, \
        "decode starved while the long prompt prefilled"
    for _ in range(100):
        if ra in results and rb in results:
            break
        results.update(eng.step())
    assert results[ra] == wa and results[rb] == wb


def test_admission_lookahead_avoids_head_of_line(params):
    """A head request that can't get pages must not block an admissible
    request behind it (bounded lookahead) — unless the head has aged
    past admit_age_cap_s, in which case freed pages are reserved for it."""
    def setup(**kw):
        eng = InferenceEngine(CFG, params, page_size=8, total_pages=8,
                              max_batch=3, max_seq_len=64,
                              prefix_cache=False, **kw)
        # decoder holding 5 of the 7 allocatable pages
        eng.add_request([(2 * i + 1) % CFG.vocab_size
                         for i in range(24)], 30)
        eng.step()
        rb = eng.add_request([(3 * i + 2) % CFG.vocab_size
                              for i in range(17)], 4)   # needs 3 pages
        rc = eng.add_request([11, 5, 42, 7, 9, 1, 3], 4)  # needs 1 page
        eng.step()
        waiting = {s.request_id for s in eng.waiting}
        return rb, rc, waiting

    rb, rc, waiting = setup()
    assert rb in waiting, "head shouldn't fit yet"
    assert rc not in waiting, "lookahead should admit the small prompt"

    # aged head (cap 0 -> instantly aged): scan freezes at the head
    rb, rc, waiting = setup(admit_age_cap_s=0.0)
    assert rb in waiting and rc in waiting, \
        "aged memory-blocked head must stop younger requests jumping it"


# ------------------------------------------------------------------- tp


def test_tp_engine_matches_single_chip(params):
    """tp=2 sharded engine (weights Megatron-split, kv-heads sharded over
    a ('tp',) mesh) reproduces the tp=1 greedy stream exactly — single
    AND batched prefill paths (reference capability: vllm_models.py
    tensor_parallel_size; here the mesh IS the worker group)."""
    kw = dict(page_size=8, total_pages=64, max_batch=4, max_seq_len=128,
              decode_chunk=4)
    e1 = InferenceEngine(CFG, params, **kw)
    e2 = InferenceEngine(CFG, params, tp=2, **kw)
    assert e2.mesh is not None and e2.mesh.shape["tp"] == 2
    prompt = [5, 17, 42, 9, 100, 3, 77]
    assert e2.generate(prompt, max_new_tokens=10) == \
        e1.generate(prompt, max_new_tokens=10)
    # multi-prompt ragged prefill under shard_map parity
    prompts = [[11, 22, 33], [101, 5, 9], [60, 61, 62, 63, 64]]
    r1 = [e1.add_request(p, 6) for p in prompts]
    r2 = [e2.add_request(p, 6) for p in prompts]
    d1, d2 = {}, {}
    for _ in range(100):
        d1.update(e1.step())
        d2.update(e2.step())
        if len(d1) == len(r1) and len(d2) == len(r2):
            break
    for a, b in zip(r1, r2):
        assert d1[a] == d2[b], (d1[a], d2[b])
    assert e2.stats["ragged_dispatches"] == e1.stats["ragged_dispatches"]


def test_tp_chunked_prefill_prefix_and_cow(params):
    """The sharded chunk-prefill and COW page-copy programs (shard_map
    over kv-head shards) reproduce the oracle stream: chunked cold
    prefill, a prefix-cache hit, and an exact-page-multiple COW."""
    eng = InferenceEngine(CFG, params, tp=2, page_size=8, total_pages=64,
                          max_batch=2, max_seq_len=128, decode_chunk=4,
                          prefill_chunk=8)
    prompt = [(5 * i + 2) % CFG.vocab_size for i in range(20)]
    want = _oracle_greedy(params, prompt, 6)
    assert eng.generate(prompt, max_new_tokens=6) == want   # chunked cold
    assert eng.stats["ragged_dispatches"] == 3
    rid = eng.add_request(prompt, 6)                        # prefix hit
    done = {}
    for _ in range(100):
        done.update(eng.step())
        if rid in done:
            break
    assert done[rid] == want
    assert eng.cached_tokens(rid) == 16
    p2 = prompt[:16]                      # exact page multiple: COW path
    assert eng.generate(p2, max_new_tokens=4) == \
        _oracle_greedy(params, p2, 4)
    assert eng.stats["cow_copies"] == 1


def test_tp_validation():
    from ray_tpu.llm.tp import validate_tp
    with pytest.raises(ValueError):
        validate_tp(CFG, 3)           # 3 does not divide n_kv_heads=4
    with pytest.raises(ValueError):
        InferenceEngine(CFG, tp=64)   # more shards than devices


def test_mixed_length_prompts_share_one_dispatch(params):
    """Wildly different prompt lengths pack into the SAME ragged
    dispatch — the case the old length-bucketed prefill could never
    batch (different compile buckets forced separate dispatches)."""
    eng = InferenceEngine(CFG, params, page_size=8, total_pages=128,
                          max_batch=4, max_seq_len=128, prefill_chunk=32)
    short = [5, 6, 7]
    long = [20 + i for i in range(20)]
    solo = [_oracle_greedy(params, p, 5) for p in (short, long)]
    rids = [eng.add_request(short, 5), eng.add_request(long, 5)]
    results = dict(eng.step())
    assert eng.stats["ragged_dispatches"] == 1, \
        "3- and 20-token prompts should prefill in one ragged dispatch"
    for _ in range(100):
        if len(results) == 2:
            break
        results.update(eng.step())
    for rid, want in zip(rids, solo):
        assert results[rid] == want


def test_compiled_step_programs_constant(params):
    """The compile-count contract: an engine serving wildly varying
    prompt lengths, chunk boundaries and batch occupancies compiles at
    most THREE step programs (ragged mixed step, decode loop, COW
    copy) — no per-length-bucket program zoo."""
    eng = InferenceEngine(CFG, params, page_size=8, total_pages=64,
                          max_batch=3, max_seq_len=80, decode_chunk=3,
                          prefill_chunk=10)
    before = eng.compiled_step_programs()
    for plen in (1, 4, 9, 10, 11, 23, 30):
        prompt = [(3 * i + 1) % CFG.vocab_size for i in range(plen)]
        eng.generate(prompt, max_new_tokens=4)
    # repeated prompt -> prefix hit; exact-page-multiple -> COW program
    eng.generate([(3 * i + 1) % CFG.vocab_size for i in range(16)], 4)
    eng.generate([(3 * i + 1) % CFG.vocab_size for i in range(16)], 4)
    assert eng.stats["cow_copies"] >= 1
    compiled = eng.compiled_step_programs() - before
    assert 1 <= compiled <= 3, \
        f"expected <=3 compiled step programs, got {compiled}"
    # spot-check parity so the count isn't trivially cheap
    p = [(3 * i + 1) % CFG.vocab_size for i in range(23)]
    assert eng.generate(p, 4) == _oracle_greedy(params, p, 4)


# ------------------------------------------------------------ int8 KV


def test_int8_kv_engine_greedy_equivalence():
    """kv_dtype="int8" (quantized pages + bf16 scales) must leave the
    greedy stream unchanged — both the chunked prefill writes and the
    decode appends round-trip through int8.

    Weights are seeded so fp argmax margins exceed int8 round-trip
    noise (~1e-2 relative); some random tiny models sit ON a tie and
    flip legitimately. A paging/indexing bug still fails loudly: a
    wrong-page read perturbs logits O(1), not O(1e-2)."""
    p8 = init_params(CFG, jax.random.PRNGKey(1))
    eng = InferenceEngine(CFG, p8, page_size=8, total_pages=64,
                          max_batch=4, max_seq_len=128, prefill_chunk=8,
                          kv_dtype="int8")
    assert eng.kv["k"].dtype == jnp.int8
    assert set(eng.kv) == {"k", "v", "k_scale", "v_scale"}
    for prompt in ([5, 17, 42, 9, 100, 3, 77],
                   [(5 * i + 2) % CFG.vocab_size for i in range(20)]):
        got = eng.generate(prompt, max_new_tokens=10)
        want = _oracle_greedy(p8, prompt, 10)
        assert got == want, f"int8 KV diverged: {got} vs {want}"


def test_int8_kv_prefix_hit_cow_and_evict(params):
    """Prefix-cache hit, COW and LRU eviction all operate on quantized
    pages (scales ride the same pytree), with hit-vs-cold invariance."""
    eng = InferenceEngine(CFG, params, page_size=8, total_pages=16,
                          max_batch=2, max_seq_len=64, prefill_chunk=8,
                          kv_dtype="int8")
    base = [(7 * i + 3) % CFG.vocab_size for i in range(16)]
    cold = eng.generate(base + [9], 6)
    rid = eng.add_request(base + [9], 6)         # full 2-page hit
    done = {}
    for _ in range(100):
        done.update(eng.step())
        if rid in done:
            break
    assert done[rid] == cold, "int8 prefix hit changed the stream"
    assert eng.cached_tokens(rid) == 16
    cow_cold = eng.generate(base, 6)             # exact page multiple
    cow0 = eng.stats["cow_copies"]
    cow_hit = eng.generate(base, 6)              # COW on shared page
    assert eng.stats["cow_copies"] == cow0 + 1
    assert cow_hit == cow_cold, "int8 COW changed the stream"
    for m in (11, 13, 17):   # distinct 5-page prompts overflow the pool
        big = [(m * i + 5) % CFG.vocab_size for i in range(40)]
        assert eng.generate(big, 4) == eng.generate(big, 4)
    assert eng.prefix.evictions >= 1, "no eviction under pressure"


def test_int8_kv_capacity_ratio():
    """The capacity claim: at head_dim 64, an int8 pool (pages + bf16
    scales) fits >= 1.9x the sequences of an fp16 pool in the same HBM
    bytes."""
    from ray_tpu.llm.cache import make_kv_cache
    cfg = LlamaConfig(vocab_size=128, dim=512, n_layers=2, n_heads=8,
                      n_kv_heads=4, ffn_dim=1024, dtype=jnp.bfloat16)
    assert cfg.head_dim == 64
    fp = make_kv_cache(cfg, total_pages=8, page_size=32)
    q8 = make_kv_cache(cfg, total_pages=8, page_size=32, kv_dtype="int8")
    fp_bytes = sum(leaf.nbytes for leaf in fp.values())
    q8_bytes = sum(leaf.nbytes for leaf in q8.values())
    assert fp_bytes / q8_bytes >= 1.9, \
        f"int8 KV capacity ratio {fp_bytes / q8_bytes:.3f} < 1.9"


def test_kv_tag_prevents_cross_scheme_hits():
    """Pages written under one KV storage scheme must never hash-match
    a lookup under another: same tokens, incompatible page bytes."""
    from ray_tpu.llm.cache import (PageAllocator, PrefixCache,
                                   hash_token_blocks)
    prompt = list(range(16))
    assert hash_token_blocks(prompt, 8, "float32") != \
        hash_token_blocks(prompt, 8, "int8")
    a = PageAllocator(16)
    c_fp = PrefixCache(a, page_size=8, kv_tag="float32")
    c_q8 = PrefixCache(a, page_size=8, kv_tag="int8")
    pages = a.alloc(2)
    c_fp.register(prompt, pages)
    assert c_fp.match(prompt)[1] > 0
    hit, matched, _ = c_q8.match(prompt)
    assert hit == [] and matched == 0, \
        "int8 lookup matched fp-written pages"
