"""LLM serving through ray_tpu.serve: a replica-hosted engine doing
continuous batching across concurrent requests (reference capability:
ray.serve.llm LLMDeployment over vLLM)."""

import pytest

import ray_tpu as rt
from ray_tpu import serve


@pytest.fixture(scope="module")
def serve_rt():
    rt.init(num_cpus=4, _system_config={
        "object_store_memory_bytes": 128 * 1024 * 1024,
    })
    yield rt
    serve.shutdown()
    rt.shutdown()


def test_llm_deployment_concurrent_requests(serve_rt):
    from ray_tpu.llm import LLMServer

    dep = serve.deployment(name="llm", max_ongoing_requests=8)(LLMServer)
    h = serve.run(dep.bind(
        {"n_layers": 2},
        {"page_size": 8, "total_pages": 64, "max_batch": 4,
         "max_seq_len": 128, "seed": 7},
    ), timeout_s=240)

    prompts = [[5, 17, 42], [5, 17, 42], [9, 9, 1, 2]]
    resps = [h.remote({"prompt_ids": p, "max_tokens": 6}) for p in prompts]
    outs = [r.result(timeout=300) for r in resps]
    assert all(len(o["token_ids"]) == 6 for o in outs)
    # same prompt -> same greedy tokens (engine must be deterministic)
    assert outs[0]["token_ids"] == outs[1]["token_ids"]
    stats = h.stats.remote().result(timeout=60)
    # continuous batching + chunking: 18 tokens in a handful of dispatches
    assert stats["decode_dispatches"] < 9, stats
