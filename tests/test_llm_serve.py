"""LLM serving through ray_tpu.serve: a replica-hosted engine doing
continuous batching across concurrent requests (reference capability:
ray.serve.llm LLMDeployment over vLLM)."""

import time

import pytest

import ray_tpu as rt
from ray_tpu import serve


@pytest.fixture(scope="module")
def serve_rt():
    # 8 TPU resources let the tp>1 deployment's derived {"TPU": tp} gang
    # reservation schedule on the test cluster; the fast telemetry period
    # lets the flight-recorder head-aggregation test poll quickly
    rt.init(num_cpus=4, resources={"TPU": 8}, _system_config={
        "object_store_memory_bytes": 128 * 1024 * 1024,
        "metrics_export_period_s": 1.0,
    })
    yield rt
    serve.shutdown()
    rt.shutdown()


def test_llm_deployment_concurrent_requests(serve_rt):
    from ray_tpu.llm import LLMServer

    dep = serve.deployment(name="llm", max_ongoing_requests=8)(LLMServer)
    h = serve.run(dep.bind(
        {"n_layers": 2},
        {"page_size": 8, "total_pages": 64, "max_batch": 4,
         "max_seq_len": 128, "seed": 7},
    ), timeout_s=240)

    prompts = [[5, 17, 42], [5, 17, 42], [9, 9, 1, 2]]
    resps = [h.remote({"prompt_ids": p, "max_tokens": 6}) for p in prompts]
    outs = [r.result(timeout=300) for r in resps]
    assert all(len(o["token_ids"]) == 6 for o in outs)
    # same prompt -> same greedy tokens (engine must be deterministic)
    assert outs[0]["token_ids"] == outs[1]["token_ids"]
    stats = h.stats.remote().result(timeout=60)
    # continuous batching + chunking: 18 tokens in a handful of dispatches
    assert stats["decode_dispatches"] < 9, stats


def test_llm_request_record_links_router_trace(serve_rt):
    """Acceptance: the trace_id the serve router stamps on the wire is
    the one in the engine's flight-recorder record, and the record ships
    to the head (requests_dump) over the telemetry plane."""
    from ray_tpu.core.worker import global_worker
    from ray_tpu.llm import LLMServer
    from ray_tpu.util import trace_context

    dep = serve.deployment(name="llm-obs", max_ongoing_requests=8)(
        LLMServer)
    h = serve.run(dep.bind(
        {"n_layers": 2},
        {"page_size": 8, "total_pages": 64, "max_batch": 4,
         "max_seq_len": 128, "seed": 7},
    ), timeout_s=240)

    tid = trace_context.new_trace_id()
    tok = trace_context.activate(tid, trace_context.new_span_id())
    try:
        out = h.remote({"prompt_ids": [5, 17, 42, 9],
                        "max_tokens": 4}).result(timeout=300)
    finally:
        trace_context.deactivate(tok)
    rid = out["request_id"]

    # replica-local view: the record carries the ROUTER's trace_id
    recs = h.request_records.remote().result(timeout=60)
    rec = {r["rid"]: r for r in recs}[rid]
    assert rec["trace_id"] == tid
    assert rec["done"] and rec["finish_reason"] == "length"
    assert rec["n_generated"] == 4 and rec["ttft"] > 0

    # head-side view: telemetry_push ships the finished record
    head = global_worker.backend.head
    deadline = time.monotonic() + 60
    got = []
    while time.monotonic() < deadline:
        got = head.call("requests_dump", {"request": rid}, timeout=10)
        if got and got[0].get("done"):
            break
        time.sleep(0.5)
    assert got, "record never reached the head"
    assert got[0]["rid"] == rid and got[0]["trace_id"] == tid
    assert got[0]["worker"] and got[0]["node"]
    slowest = head.call("requests_dump", {"slowest": 5}, timeout=10)
    assert any(r["rid"] == rid for r in slowest)
    serve.delete("llm-obs")


def test_llm_tp_deployment_gang_resources(serve_rt):
    """A tp=2 engine deploys through build_llm_app: replica resources are
    DERIVED from the tp degree ({'TPU': 2} STRICT_PACK gang — reference:
    vllm_models.py:128-153 placement from TP×PP), the replica worker
    shards the engine over a 2-device mesh (virtual CPU devices via the
    deployment's runtime_env), and generation matches the tp=1
    deployment's greedy stream."""
    from ray_tpu.llm import build_llm_app, placement_for_engine

    bundles, strategy = placement_for_engine(tp=2)
    assert bundles == [{"TPU": 2.0}] and strategy == "STRICT_PACK"
    bundles, strategy = placement_for_engine(tp=8, pp=2)
    assert bundles == [{"TPU": 8.0}] * 2 and strategy == "PACK"

    model_cfg = {"n_layers": 2}
    eng_cfg = {"page_size": 8, "total_pages": 64, "max_batch": 4,
               "max_seq_len": 128, "seed": 7}
    env = {"env_vars": {
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
    }}
    app_tp = build_llm_app(model_cfg, {**eng_cfg, "tp": 2}, name="llm-tp2",
                           runtime_env=env)
    h_tp = serve.run(app_tp, timeout_s=300)
    out_tp = h_tp.remote(
        {"prompt_ids": [5, 17, 42, 9], "max_tokens": 6}).result(timeout=300)

    app_1 = build_llm_app(model_cfg, eng_cfg, name="llm-tp1",
                          runtime_env=env)
    h_1 = serve.run(app_1, timeout_s=300)
    out_1 = h_1.remote(
        {"prompt_ids": [5, 17, 42, 9], "max_tokens": 6}).result(timeout=300)
    assert out_tp["token_ids"] == out_1["token_ids"]

    # the tp replica really reserved its chip gang on the node
    avail = serve_rt.available_resources()
    assert avail.get("TPU", 0) <= 6.0, avail
    serve.delete("llm-tp2")
    serve.delete("llm-tp1")
