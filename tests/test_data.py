"""ray_tpu.data tests.

Coverage model mirrors the reference's data tests (reference:
python/ray/data/tests/test_map.py, test_consumption.py,
test_streaming_executor.py scope): constructors, transforms, limit
pushdown, exact-batch iteration, splits for train ingest, file readers,
and the Train integration path.
"""

import json
import os

import numpy as np
import pytest

import ray_tpu as rt
from ray_tpu import data as rd


@pytest.fixture(scope="module")
def local_rt():
    rt.init(local_mode=True, num_cpus=4)
    yield rt
    rt.shutdown()


# ------------------------------------------------------------ constructors


def test_range_count_take(local_rt):
    ds = rd.range(100, num_blocks=7)
    assert ds.num_blocks() == 7
    assert ds.count() == 100
    rows = ds.take(5)
    assert [int(r["id"]) for r in rows] == [0, 1, 2, 3, 4]


def test_from_items_and_schema(local_rt):
    ds = rd.from_items([{"x": i, "y": 2 * i} for i in range(10)],
                       num_blocks=3)
    schema = ds.schema()
    assert set(schema) == {"x", "y"}
    assert ds.count() == 10


def test_from_numpy_roundtrip(local_rt):
    arr = np.arange(20, dtype=np.float32).reshape(20)
    ds = rd.from_numpy(arr, num_blocks=4)
    out = np.concatenate(
        list(ds.iter_batches(batch_size=6, batch_format="numpy")))
    np.testing.assert_array_equal(out, arr)


# -------------------------------------------------------------- transforms


def test_map_filter_flat_map(local_rt):
    ds = (rd.range(20, num_blocks=4)
          .map(lambda r: {"id": r["id"] * 10})
          .filter(lambda r: r["id"] % 20 == 0)
          .flat_map(lambda r: [r, r]))
    vals = sorted(int(r["id"]) for r in ds.iter_rows())
    assert vals == sorted(2 * [i * 10 for i in range(20) if (i * 10) % 20 == 0])


def test_map_batches_columnar(local_rt):
    ds = rd.range(32, num_blocks=4).map_batches(
        lambda b: {"id": b["id"], "sq": b["id"] ** 2}, batch_size=8)
    batch = next(ds.iter_batches(batch_size=32))
    np.testing.assert_array_equal(batch["sq"], np.arange(32) ** 2)


def test_map_batches_numpy_format(local_rt):
    ds = rd.from_numpy(np.ones(16), num_blocks=2).map_batches(
        lambda a: a * 3.0, batch_format="numpy")
    out = np.concatenate(
        list(ds.iter_batches(batch_size=8, batch_format="numpy")))
    np.testing.assert_allclose(out, 3.0)


def test_limit_pushdown_stops_submission(local_rt):
    ds = rd.range(1000, num_blocks=100).limit(5)
    assert [int(r["id"]) for r in ds.iter_rows()] == [0, 1, 2, 3, 4]
    # limit(5) over 10-row blocks must not have executed all 100 block tasks
    assert ds.stats()["tasks"] <= 10


def test_union_and_shuffle(local_rt):
    a = rd.range(10, num_blocks=2).map(lambda r: {"id": r["id"]})
    b = rd.range(10, num_blocks=2).map(lambda r: {"id": r["id"] + 100})
    u = a.union(b)
    assert u.count() == 20
    sh = rd.range(50, num_blocks=5).random_shuffle(seed=7)
    vals = [int(r["id"]) for r in sh.iter_rows()]
    assert sorted(vals) == list(range(50))
    assert vals != list(range(50)), "shuffle must change order"


def test_repartition(local_rt):
    ds = rd.range(30, num_blocks=3).repartition(5)
    assert ds.num_blocks() == 5
    assert ds.count() == 30


# ---------------------------------------------------------------- batching


def test_iter_batches_exact_sizes(local_rt):
    ds = rd.range(25, num_blocks=4)
    sizes = [len(b["id"]) for b in ds.iter_batches(batch_size=8)]
    assert sizes == [8, 8, 8, 1]
    sizes = [len(b["id"])
             for b in ds.iter_batches(batch_size=8, drop_last=True)]
    assert sizes == [8, 8, 8]


def test_iter_jax_batches_pads_static_shape(local_rt):
    it = rd.DataIterator(rd.range(25, num_blocks=4))
    batches = list(it.iter_jax_batches(batch_size=8))
    assert all(len(b["id"]) == 8 for b in batches)
    last = batches[-1]
    assert last["__valid__"].sum() == 1 and last["__valid__"][0]
    total_valid = sum(int(b["__valid__"].sum()) for b in batches)
    assert total_valid == 25


def test_split_disjoint_and_complete(local_rt):
    ds = rd.range(40, num_blocks=8)
    shards = ds.split(3)
    assert sum(s.num_blocks() for s in shards) == 8
    seen = []
    for s in shards:
        seen.extend(int(r["id"]) for r in s.iter_rows())
    assert sorted(seen) == list(range(40))


def test_materialize_pins_blocks(local_rt):
    ds = rd.range(20, num_blocks=2).map(lambda r: {"id": r["id"] + 1})
    mat = ds.materialize()
    # re-iterating a materialized dataset re-reads the stored blocks
    assert mat.count() == 20
    assert sorted(int(r["id"]) for r in mat.iter_rows()) == \
        list(range(1, 21))


# ------------------------------------------------------------ file readers


def test_read_text_and_json(local_rt, tmp_path):
    p1 = tmp_path / "a.txt"
    p1.write_text("alpha\nbeta\n")
    p2 = tmp_path / "b.txt"
    p2.write_text("gamma\n")
    ds = rd.read_text(str(tmp_path))
    assert sorted(ds.iter_rows()) == ["alpha", "beta", "gamma"]

    j = tmp_path / "rows.jsonl"
    with open(j, "w") as f:
        for i in range(5):
            f.write(json.dumps({"v": i}) + "\n")
    ds = rd.read_json(str(j))
    assert sorted(int(r["v"]) for r in ds.iter_rows()) == list(range(5))


def test_read_npy_and_csv(local_rt, tmp_path):
    np.save(tmp_path / "x.npy", np.arange(6))
    ds = rd.read_npy(str(tmp_path / "x.npy"))
    np.testing.assert_array_equal(
        next(ds.iter_batches(batch_size=6, batch_format="numpy")),
        np.arange(6))

    c = tmp_path / "t.csv"
    c.write_text("a,b\n1,2\n3,4\n")
    ds = rd.read_csv(str(c))
    batch = next(ds.iter_batches(batch_size=2))
    np.testing.assert_array_equal(batch["a"], [1, 3])
    np.testing.assert_array_equal(batch["b"], [2, 4])


# ------------------------------------------------------- train integration


def test_trainer_with_dataset_shards(local_rt):
    from ray_tpu import train

    def loop(cfg):
        ctx = train.get_context()
        it = train.get_dataset_shard("train")
        total = 0
        n = 0
        for batch in it.iter_batches(batch_size=4):
            total += int(batch["id"].sum())
            n += len(batch["id"])
        train.report({"rows": n, "sum": total, "rank": ctx.get_rank()})

    ds = rd.range(24, num_blocks=6)
    trainer = train.JaxTrainer(
        loop, train_loop_config={},
        scaling_config=train.ScalingConfig(num_workers=2),
        run_config=train.RunConfig(),
        datasets={"train": ds})
    result = trainer.fit()
    assert result.metrics["rows"] == 12  # rank 0's disjoint half
