"""LLM request flight recorder: record timing math, ring bounds, SLO
accounting, telemetry export — plus the engine lifecycle end-to-end
(finish reasons, recompute preemption with both record phases, eviction
of unsatisfiable working sets).

The recorder module itself must import (and run) without jax: the
cluster backend's telemetry thread drains it from any worker, and the
pure-record tests here are part of the tier-1 CPU sweep.
"""

import subprocess
import sys

import pytest

from ray_tpu.llm.request_log import (DECODE_ENTRY_CAP, FlightRecorder,
                                     RequestRecord, drain_all_exports)

# ------------------------------------------------------------ pure record


def _rec(**kw):
    kw.setdefault("rid", "r0")
    kw.setdefault("prompt_tokens", 8)
    kw.setdefault("max_new_tokens", 4)
    return RequestRecord(kw.pop("rid"), kw.pop("prompt_tokens"),
                         kw.pop("max_new_tokens"), **kw)


def test_record_timing_math():
    r = _rec(trace_id="t-abc")
    t0 = r.t0
    r.note_admit(t0 + 0.001, cached_tokens=3)
    r.note_chunk(t0 + 0.003, n_tokens=5, dispatch_idx=7)
    r.note_decode(t0 + 0.005, 1)   # first token -> TTFT
    r.note_decode(t0 + 0.006, 1)
    r.note_decode(t0 + 0.007, 1)
    assert r.queue_wait == pytest.approx(0.001)
    assert r.cached_tokens() == 3
    assert r.ttft == pytest.approx(0.005)
    assert r.n_generated == 3
    # TPOT = (last - first) / (n - 1); first token is not an entry
    assert r.tpot == pytest.approx(0.001)
    assert r.decode_entries() == [
        (pytest.approx(0.001), 1), (pytest.approx(0.001), 1)]

    d = r.to_dict()
    assert d["rid"] == "r0" and d["trace_id"] == "t-abc"
    assert d["chunks"] == [[pytest.approx(0.003), 5, 7]]
    assert d["admits"] == [[pytest.approx(0.001), 3]]
    assert not d["done"] and d["finish_reason"] is None


def test_record_single_token_has_no_tpot():
    r = _rec()
    r.note_decode(r.t0 + 0.004, 1)
    assert r.ttft == pytest.approx(0.004)
    assert r.tpot is None and r.n_generated == 1


def test_note_first_idempotent_across_preemption():
    r = _rec()
    r.note_first(r.t0 + 0.002)
    r.note_preempt(r.t0 + 0.003)
    r.note_admit(r.t0 + 0.004, 0)   # re-admit: second phase
    r.note_first(r.t0 + 0.009)      # re-prefill must NOT move TTFT
    assert r.ttft == pytest.approx(0.002)
    assert len(r.admits) == 1 and len(r.preempt_ts) == 1
    assert r.to_dict()["preempts"] == 1


def test_record_decode_entry_cap_overflow_aggregates():
    r = _rec(max_new_tokens=10_000)
    t, n = r.t0, 0
    for i in range(DECODE_ENTRY_CAP + 40):
        t += 0.001
        r.note_decode(t, 2)
        n += 2
    assert r.n_generated == n
    # first call set TTFT (no entry); cap entries kept verbatim
    assert len(r.decode_entries()) == DECODE_ENTRY_CAP
    assert r.to_dict()["decode_overflow_tokens"] == (40 - 1) * 2
    # aggregates stay exact past the cap: TPOT uses last_ts, not entries
    # (2 tokens per dispatch -> per-token latency is half the interval)
    n_calls = DECODE_ENTRY_CAP + 40
    assert r.tpot == pytest.approx((n_calls - 1) * 0.001 / (n - 1),
                                   rel=1e-6)


# ---------------------------------------------------------------- recorder


def _finished(fr, rid, ttft=0.01, tpot=0.001, n=4):
    rec = fr.start(rid, 8, n)
    rec.note_admit(rec.t0 + 0.001, 0)
    t = rec.t0 + ttft
    rec.note_decode(t, 1)
    for _ in range(n - 1):
        t += tpot
        rec.note_decode(t, 1)
    fr.finish(rec, t + 0.001, "length")
    return rec


def test_ring_eviction_prefers_finished():
    fr = FlightRecorder(capacity=3, observe_metrics=False)
    live_a = fr.start("live-a", 4, 4)
    _finished(fr, "fin-b")
    live_c = fr.start("live-c", 4, 4)
    fr.start("live-d", 4, 4)        # over capacity: evicts fin-b first
    assert fr.get("fin-b") is None
    assert fr.get("live-a") is live_a and fr.get("live-c") is live_c
    fr.start("live-e", 4, 4)        # all live: oldest live goes
    assert fr.get("live-a") is None
    assert len(fr) == 3


def test_ring_eviction_over_capacity_bulk():
    fr = FlightRecorder(capacity=8, observe_metrics=False)
    for i in range(50):
        _finished(fr, f"r{i}")
    assert len(fr) == 8
    kept = {d["rid"] for d in fr.snapshot()}
    assert kept == {f"r{i}" for i in range(42, 50)}  # newest survive


def test_finish_idempotent_and_slo_attainment():
    fr = FlightRecorder(capacity=8, observe_metrics=False,
                        slo_ttft_s=0.02, slo_tpot_s=0.002)
    good = _finished(fr, "good", ttft=0.01, tpot=0.001)
    fr.finish(good, good.t0 + 99.0, "stop")  # second finish: no-op
    assert good.finish_reason == "length"
    assert fr.n_finished == 1
    _finished(fr, "slow-ttft", ttft=0.05, tpot=0.001)
    _finished(fr, "slow-tpot", ttft=0.01, tpot=0.01)
    ttft_ok, tpot_ok = fr.slo_attainment()
    assert ttft_ok == pytest.approx(2 / 3)
    assert tpot_ok == pytest.approx(2 / 3)
    # 1-token request: no inter-token latency -> cannot miss TPOT
    one = fr.start("one", 4, 1)
    one.note_decode(one.t0 + 0.01, 1)
    fr.finish(one, one.t0 + 0.011, "length")
    assert fr.slo_attainment()[1] == pytest.approx(3 / 4)


def test_slo_attainment_empty_is_perfect():
    fr = FlightRecorder(capacity=4, observe_metrics=False)
    assert fr.slo_attainment() == (1.0, 1.0)


def test_drain_export_finished_plus_live():
    fr = FlightRecorder(capacity=8, observe_metrics=False)
    _finished(fr, "done-1")
    live = fr.start("live-1", 4, 4)
    live.note_decode(live.t0 + 0.01, 1)
    out = fr.drain_export()
    by_rid = {d["rid"]: d for d in out}
    assert by_rid["done-1"]["done"] and by_rid["done-1"]["e2e"] > 0
    assert not by_rid["live-1"]["done"]
    # finished records drain ONCE; live snapshots re-ship every flush
    again = {d["rid"] for d in fr.drain_export()}
    assert again == {"live-1"}
    assert "live-1" in {d["rid"] for d in drain_all_exports()}


def test_finish_observes_serving_histograms():
    from ray_tpu.util import metrics as metrics_mod
    metrics_mod.clear_registry()
    try:
        fr = FlightRecorder(capacity=4)  # observe_metrics default on
        _finished(fr, "obs-1", ttft=0.01, tpot=0.001, n=4)
        snap = metrics_mod.snapshot()
        for name in ("llm_ttft_seconds", "llm_tpot_seconds",
                     "llm_e2e_seconds", "llm_queue_wait_seconds"):
            fam = snap[name]
            assert fam["type"] == "histogram", name
            (hist,) = fam["values"].values()
            assert hist["n"] == 1, name
        assert snap["llm_ttft_seconds"]["values"][()]["sum"] == \
            pytest.approx(0.01)
    finally:
        metrics_mod.clear_registry()


def test_request_log_imports_without_jax():
    """Tier-1 contract: the recorder (and constructing one, metrics
    included) must not pull the accelerator stack into the process."""
    code = ("import sys; import ray_tpu.llm.request_log as rl; "
            "rl.FlightRecorder(capacity=4); "
            "import ray_tpu.llm; ray_tpu.llm.FlightRecorder; "
            "print('jax' in sys.modules)")
    out = subprocess.run([sys.executable, "-c", code],
                         capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr
    assert out.stdout.strip() == "False", out.stdout


# ------------------------------------------------------- engine lifecycle


@pytest.fixture(scope="module")
def tiny_cfg():
    jnp = pytest.importorskip("jax.numpy")
    from ray_tpu.models.llama import LlamaConfig
    return LlamaConfig.tiny(n_layers=2, dtype=jnp.float32)


def _run(eng):
    done = {}
    while eng.has_work():
        done.update(eng.step())
    return done


def test_engine_records_full_lifecycle(tiny_cfg):
    from ray_tpu.llm import InferenceEngine
    eng = InferenceEngine(tiny_cfg, page_size=8, total_pages=64,
                          max_batch=4, max_seq_len=128, seed=7)
    rids = [eng.add_request([5 + i, 17, 42, 9, 100, 3, 77, i + 1],
                            max_new_tokens=12, trace_id=f"tid{i}")
            for i in range(3)]
    done = _run(eng)
    assert set(done) == set(rids)
    records = {d["rid"]: d for d in eng.request_log.snapshot()}
    for i, rid in enumerate(rids):
        d = records[rid]
        assert d["trace_id"] == f"tid{i}"
        assert d["done"] and d["finish_reason"] == "length"
        assert d["n_generated"] == 12
        assert d["prompt_tokens"] == 8 and d["max_new_tokens"] == 12
        assert len(d["admits"]) == 1 and d["queue_wait"] >= 0
        assert d["chunks"], "prefill chunks must be recorded"
        assert sum(c[1] for c in d["chunks"]) == 8
        assert 0 < d["ttft"] <= d["e2e"]
        assert d["tpot"] is not None and d["tpot"] >= 0
    # SLO gauges follow the recorder
    ttft_ok, tpot_ok = eng.request_log.slo_attainment()
    assert 0.0 <= ttft_ok <= 1.0 and 0.0 <= tpot_ok <= 1.0


def test_engine_finish_reason_stop_records(tiny_cfg):
    from ray_tpu.llm import InferenceEngine
    eng = InferenceEngine(tiny_cfg, page_size=8, total_pages=64,
                          max_batch=4, max_seq_len=128, seed=7)
    probe = eng.generate([5, 17, 42, 9], max_new_tokens=8)
    # eos = the first token NOT emitted earlier in the greedy stream, so
    # the engine stops exactly at its first occurrence
    k = next(i for i, t in enumerate(probe) if t not in probe[:i] and i)
    eng2 = InferenceEngine(tiny_cfg, page_size=8, total_pages=64,
                           max_batch=4, max_seq_len=128, seed=7,
                           eos_token=probe[k])
    rid = eng2.add_request([5, 17, 42, 9], max_new_tokens=12)
    done = _run(eng2)
    assert done[rid] == probe[:k]
    d = {r["rid"]: r for r in eng2.request_log.snapshot()}[rid]
    assert d["finish_reason"] == "stop" and d["done"]
    assert eng2.finish_reason(rid) == "stop"


def test_engine_preemption_recompute_parity_and_record(tiny_cfg):
    """Under a page pool too small for both sequences, the loser is
    recompute-preempted (pages dropped, re-queued, re-prefilled) and its
    record carries BOTH phases; greedy argmax makes the final tokens
    IDENTICAL to an uncontended run."""
    from ray_tpu.llm import InferenceEngine
    kw = dict(page_size=4, max_batch=4, max_seq_len=32, seed=7,
              prefix_cache=False, decode_chunk=4)
    p1, p2 = list(range(1, 9)), list(range(3, 11))

    ref = InferenceEngine(tiny_cfg, total_pages=64, **kw)
    q1 = ref.add_request(list(p1), max_new_tokens=16)
    q2 = ref.add_request(list(p2), max_new_tokens=16)
    ref_done = _run(ref)
    assert ref.stats["preemptions"] == 0

    eng = InferenceEngine(tiny_cfg, total_pages=10, **kw)
    r1 = eng.add_request(list(p1), max_new_tokens=16)
    r2 = eng.add_request(list(p2), max_new_tokens=16)
    done = _run(eng)

    assert eng.stats["preemptions"] >= 1
    assert done[r1] == ref_done[q1] and done[r2] == ref_done[q2]
    records = {d["rid"]: d for d in eng.request_log.snapshot()}
    preempted = [d for d in records.values() if d["preempts"] >= 1]
    assert preempted, records
    for d in preempted:
        # both phases in one record: re-admit after the preempt
        assert len(d["admits"]) == d["preempts"] + 1
        assert d["preempt_ts"] and d["stalls"] >= d["preempts"]
        assert d["finish_reason"] == "length" and d["n_generated"] == 16
    assert eng.request_log.n_preempts >= 1


def test_engine_unsatisfiable_working_set_finishes_evict(tiny_cfg):
    """A sequence whose grown working set can never fit the pool stops
    with reason "evict" instead of ping-ponging forever."""
    from ray_tpu.llm import InferenceEngine
    eng = InferenceEngine(tiny_cfg, page_size=4, total_pages=4,
                          max_batch=2, max_seq_len=32, seed=7,
                          prefix_cache=False, decode_chunk=2)
    rid = eng.add_request([1, 2, 3, 4], max_new_tokens=24)
    done = _run(eng)
    assert rid in done
    assert eng.finish_reason(rid) == "evict"
    d = {r["rid"]: r for r in eng.request_log.snapshot()}[rid]
    assert d["finish_reason"] == "evict" and d["done"]
    assert 0 < d["n_generated"] < 24
    # the caller still gets every token generated before eviction
    assert len(done[rid]) == d["n_generated"]


def test_engine_recorder_disable_flag(tiny_cfg):
    from ray_tpu.llm import InferenceEngine
    eng = InferenceEngine(tiny_cfg, page_size=8, total_pages=64,
                          max_batch=2, max_seq_len=64, seed=7,
                          request_log=False)
    assert eng.request_log is None
    assert eng.generate([5, 17, 42], max_new_tokens=4)
