"""Host-side collective group tests (reference scope:
util/collective tests — allreduce/allgather/reducescatter/broadcast
across actor processes)."""

import numpy as np
import pytest

import ray_tpu as rt


@pytest.fixture(scope="module")
def cluster_rt():
    rt.init(num_cpus=4, _system_config={
        "object_store_memory_bytes": 64 * 1024 * 1024,
    })
    yield rt
    rt.shutdown()


def test_collectives_across_actor_processes(cluster_rt):
    @rt.remote
    class Member:
        def __init__(self, rank, world):
            from ray_tpu.util.collective import init_collective_group
            self.g = init_collective_group("g1", world, rank)
            self.rank = rank

        def run_all(self):
            import numpy as np
            out = {}
            out["allreduce"] = self.g.allreduce(
                np.full(4, self.rank + 1.0))          # sum over ranks
            out["mean"] = self.g.allreduce(
                np.full(2, float(self.rank)), op="mean")
            out["gather"] = [float(a[0]) for a in self.g.allgather(
                np.asarray([10.0 * self.rank]))]
            out["scatter"] = self.g.reducescatter(
                np.arange(6, dtype=np.float64))       # sum then split
            out["bcast"] = self.g.broadcast(
                np.asarray([42.0 + self.rank]), src_rank=1)
            return out

    world = 3
    members = [Member.remote(r, world) for r in range(world)]
    outs = rt.get([m.run_all.remote() for m in members], timeout=120)
    for rank, out in enumerate(outs):
        np.testing.assert_allclose(out["allreduce"], np.full(4, 6.0))
        np.testing.assert_allclose(out["mean"], np.ones(2))
        assert out["gather"] == [0.0, 10.0, 20.0]
        np.testing.assert_allclose(out["bcast"], [43.0])
    # reducescatter: rank r gets its split of sum(3 x arange(6))
    full = 3 * np.arange(6, dtype=np.float64)
    splits = np.array_split(full, world)
    for rank, out in enumerate(outs):
        np.testing.assert_allclose(out["scatter"], splits[rank])
