"""Job submission, lazy DAGs, durable workflows (reference scope:
dashboard/modules/job, ray.dag bind/execute, ray.workflow recovery)."""

import os
import sys
import time
import uuid

import pytest

import ray_tpu as rt
from ray_tpu import workflow
from ray_tpu.dag import InputNode, execute_with_input
from ray_tpu.jobs import FAILED, SUCCEEDED, JobSubmissionClient


@pytest.fixture(scope="module")
def cluster_rt():
    rt.init(num_cpus=3, _system_config={
        "object_store_memory_bytes": 64 * 1024 * 1024,
    })
    yield rt
    rt.shutdown()


# ---------------------------------------------------------------------- dag


def test_dag_bind_execute(cluster_rt):
    @rt.remote
    def add(a, b):
        return a + b

    @rt.remote
    def mul(a, b):
        return a * b

    dag = mul.bind(add.bind(1, 2), add.bind(3, 4))
    assert rt.get(dag.execute(), timeout=60) == 21


def test_dag_diamond_runs_shared_node_once(cluster_rt):
    marker = f"/tmp/rtpu_dag_{uuid.uuid4().hex[:8]}"

    @rt.remote
    def base(path):
        with open(path, "a") as f:
            f.write("x")
        return 10

    @rt.remote
    def inc(v):
        return v + 1

    @rt.remote
    def total(a, b):
        return a + b

    shared = base.bind(marker)
    dag = total.bind(inc.bind(shared), inc.bind(shared))
    try:
        assert rt.get(dag.execute(), timeout=60) == 22
        assert open(marker).read() == "x", "shared node ran more than once"
    finally:
        if os.path.exists(marker):
            os.unlink(marker)


def test_dag_input_node(cluster_rt):
    @rt.remote
    def double(x):
        return 2 * x

    @rt.remote
    def add1(x):
        return x + 1

    with InputNode() as inp:
        dag = add1.bind(double.bind(inp))
    assert rt.get(execute_with_input(dag, 5), timeout=60) == 11
    assert rt.get(execute_with_input(dag, 7), timeout=60) == 15


# ----------------------------------------------------------------- workflow


def test_workflow_resumes_from_checkpoints(cluster_rt, tmp_path):
    side = f"/tmp/rtpu_wf_{uuid.uuid4().hex[:8]}"
    crash = side + ".crash"

    @rt.remote
    def step_a():
        with open(side + ".a", "a") as f:
            f.write("a")
        return 5

    @rt.remote
    def step_b(v):
        if os.path.exists(crash):
            os.unlink(crash)
            raise RuntimeError("boom-first-run")
        with open(side + ".b", "a") as f:
            f.write("b")
        return v * 2

    dag = step_b.bind(step_a.bind())
    open(crash, "w").close()
    try:
        with pytest.raises(Exception, match="boom-first-run"):
            workflow.run(dag, workflow_id="wf1", storage=str(tmp_path))
        # resume: step_a must replay from its checkpoint, not re-run
        out = workflow.run(dag, workflow_id="wf1", storage=str(tmp_path))
        assert out == 10
        assert workflow.run.last_stats == {"steps_run": 1,
                                           "steps_replayed": 1}
        assert open(side + ".a").read() == "a"
        assert open(side + ".b").read() == "b"
        # third run replays everything
        assert workflow.run(dag, workflow_id="wf1",
                            storage=str(tmp_path)) == 10
        assert workflow.run.last_stats["steps_run"] == 0
    finally:
        for suffix in (".a", ".b"):
            if os.path.exists(side + suffix):
                os.unlink(side + suffix)
        workflow.delete("wf1", storage=str(tmp_path))


def test_workflow_dynamic_continuation(cluster_rt, tmp_path):
    """A step that returns continuation(sub_dag) is replaced by the
    sub-graph (reference: dynamic workflows, workflow_executor.py:32)."""

    @rt.remote
    def leaf(x):
        return x + 1

    @rt.remote
    def fanout(n):
        # decide the rest of the graph at runtime
        from ray_tpu import workflow as wf
        return wf.continuation(leaf.bind(n * 10))

    dag = fanout.bind(3)
    out = workflow.run(dag, workflow_id="wf_dyn", storage=str(tmp_path))
    assert out == 31
    # resume replays BOTH the parent and the continuation steps
    assert workflow.run(dag, workflow_id="wf_dyn",
                        storage=str(tmp_path)) == 31
    assert workflow.run.last_stats["steps_run"] == 0
    assert workflow.get_status("wf_dyn", storage=str(tmp_path)) == \
        workflow.COMPLETED
    workflow.delete("wf_dyn", storage=str(tmp_path))


def test_workflow_event_wait_and_signal(cluster_rt, tmp_path):
    """event() blocks until signal() delivers; delivery is durable so a
    re-run replays past the event (reference: event_listener.py)."""
    import threading

    @rt.remote
    def after_event(v):
        return f"got:{v}"

    dag = after_event.bind(workflow.event("approve", timeout_s=30.0))

    def deliver():
        time.sleep(0.4)
        workflow.signal("wf_ev", "approve", "yes", storage=str(tmp_path))

    t = threading.Thread(target=deliver)
    t.start()
    out = workflow.run(dag, workflow_id="wf_ev", storage=str(tmp_path))
    t.join()
    assert out == "got:yes"
    # durable: a fresh run sees the delivered event without re-waiting
    assert workflow.run(dag, workflow_id="wf_ev",
                        storage=str(tmp_path)) == "got:yes"
    workflow.delete("wf_ev", storage=str(tmp_path))


def test_workflow_cancel_and_status(cluster_rt, tmp_path):
    @rt.remote
    def slow_step():
        time.sleep(0.2)
        return 1

    @rt.remote
    def never_runs(v):
        return v

    # cancel before start: the run stops at its first step boundary
    workflow.cancel("wf_cancel", storage=str(tmp_path))
    dag = never_runs.bind(slow_step.bind())
    with pytest.raises(workflow.WorkflowCancelledError):
        workflow.run(dag, workflow_id="wf_cancel", storage=str(tmp_path))
    assert workflow.get_status("wf_cancel", storage=str(tmp_path)) == \
        workflow.CANCELLED
    ids = [w["workflow_id"] for w in workflow.list_all(str(tmp_path))]
    assert "wf_cancel" in ids
    workflow.delete("wf_cancel", storage=str(tmp_path))


def test_workflow_resume_api_and_step_retries(cluster_rt, tmp_path):
    """resume(workflow_id) re-runs from the STORED graph; a flaky step
    retries max_step_retries times (reference: step max_retries)."""
    flake = f"/tmp/rtpu_wf_flake_{uuid.uuid4().hex[:8]}"

    @rt.remote
    def flaky():
        if not os.path.exists(flake):
            open(flake, "w").close()
            raise RuntimeError("first attempt dies")
        return 7

    @rt.remote
    def double(v):
        return v * 2

    dag = double.bind(flaky.bind())
    try:
        out = workflow.run(dag, workflow_id="wf_retry",
                           storage=str(tmp_path), max_step_retries=2)
        assert out == 14
        # resume with NO dag argument — from storage
        assert workflow.resume("wf_retry", storage=str(tmp_path)) == 14
        assert workflow.run.last_stats["steps_run"] == 0
    finally:
        if os.path.exists(flake):
            os.unlink(flake)
        workflow.delete("wf_retry", storage=str(tmp_path))


def test_workflow_run_async(cluster_rt, tmp_path):
    @rt.remote
    def add(a, b):
        return a + b

    ref = workflow.run_async(add.bind(2, 3), workflow_id="wf_async",
                             storage=str(tmp_path))
    assert rt.get(ref, timeout=60) == 5
    assert workflow.get_status("wf_async", storage=str(tmp_path)) == \
        workflow.COMPLETED
    workflow.delete("wf_async", storage=str(tmp_path))


# --------------------------------------------------------------------- jobs


def test_job_submit_success_and_logs(cluster_rt, tmp_path):
    script = tmp_path / "job_ok.py"
    script.write_text(
        "import os, sys\n"
        "sys.path.insert(0, '/root/repo')\n"
        "import ray_tpu as rt\n"
        "rt.init(address=os.environ['RTPU_ADDRESS'])\n"
        "@rt.remote\n"
        "def sq(x):\n"
        "    return x * x\n"
        "print('RESULT', sum(rt.get([sq.remote(i) for i in range(5)],"
        " timeout=60)))\n"
        "rt.shutdown()\n")
    client = JobSubmissionClient()
    job_id = client.submit_job(
        entrypoint=f"{sys.executable} {script}")
    assert client.wait(job_id, timeout=240) == SUCCEEDED
    logs = client.get_job_logs(job_id)
    assert "RESULT 30" in logs
    jobs = client.list_jobs()
    assert any(j["job_id"] == job_id and j["status"] == SUCCEEDED
               for j in jobs)


def test_job_failure_surfaces(cluster_rt):
    client = JobSubmissionClient()
    job_id = client.submit_job(entrypoint=f"{sys.executable} -c 'import sys; sys.exit(3)'")
    assert client.wait(job_id, timeout=120) == FAILED
    assert "exit code 3" in client.get_job_info(job_id)["message"]


def test_compiled_dag_fuses_to_one_program(cluster_rt):
    """experimental_compile: the whole bound graph becomes ONE jitted
    XLA program whose result matches the task-path execution exactly
    (reference: dag/compiled_dag_node.py aDAG role)."""
    import jax.numpy as jnp
    import numpy as np
    from ray_tpu.dag import InputNode, experimental_compile, \
        execute_with_input

    @rt.remote
    def scale(x):
        return x * 2.0

    @rt.remote
    def shift(x):
        return x + 1.0

    @rt.remote
    def combine(a, b):
        return a * b          # diamond: both branches from one input

    with InputNode() as inp:
        dag = combine.bind(scale.bind(inp), shift.bind(inp))

    x = jnp.asarray([1.0, 2.0, 3.0])
    compiled = experimental_compile(dag)
    fused = compiled.execute(x)                       # no tasks at all
    via_tasks = rt.get(execute_with_input(dag, np.asarray(x)), timeout=60)
    np.testing.assert_allclose(np.asarray(fused), np.asarray(via_tasks),
                               rtol=1e-6)
    # repeat executions reuse the compiled program (fast path exists)
    np.testing.assert_allclose(np.asarray(compiled.execute(x * 2)),
                               np.asarray(x * 2) * 2 * (np.asarray(x * 2) + 1))


def test_compiled_actor_dag_pipeline(cluster_rt):
    """Cross-actor compiled DAG: pre-launched loops + shm channel rings
    (reference aDAG, compiled_dag_node.py:767). Correctness, error
    propagation, and the VERDICT #7 done-criterion: steady-state
    throughput >= 2x eager chained actor calls."""
    import time

    from ray_tpu.dag import InputNode, experimental_compile

    @rt.remote(num_cpus=0)
    class Doubler:
        def f(self, x):
            if x == "boom":
                raise ValueError("boom-input")
            return x * 2

    @rt.remote(num_cpus=0)
    class AddOne:
        def g(self, x):
            return x + 1

    a, b = Doubler.remote(), AddOne.remote()
    # warm the actors (placement + construction out of the measurement)
    assert rt.get(b.g.remote(rt.get(a.f.remote(1)))) == 3

    with InputNode() as inp:
        dag = b.g.bind(a.f.bind(inp))
    compiled = experimental_compile(dag)
    try:
        # correctness + ordering under pipelined submission
        refs = [compiled.execute(i) for i in range(20)]
        assert [r.get() for r in refs] == [2 * i + 1 for i in range(20)]

        # error propagation: the exception travels the channel and the
        # pipeline keeps working afterwards
        bad = compiled.execute("boom")
        ok = compiled.execute(5)
        with pytest.raises(ValueError, match="boom-input"):
            bad.get()
        assert ok.get() == 11

        # ---- A/B: eager chained calls vs the compiled pipeline ----
        # (get-between is the FASTER eager form here — ref-arg chaining
        # pays cross-actor object resolution — so it is the fair baseline)
        N = 200
        t0 = time.perf_counter()
        for i in range(N):
            rt.get(b.g.remote(rt.get(a.f.remote(i))))
        eager_rate = N / (time.perf_counter() - t0)

        t0 = time.perf_counter()
        refs = [compiled.execute(i) for i in range(N)]
        out = [r.get() for r in refs]
        compiled_rate = N / (time.perf_counter() - t0)
        assert out[-1] == 2 * (N - 1) + 1
        speedup = compiled_rate / eager_rate
        print(f"eager {eager_rate:.0f}/s compiled {compiled_rate:.0f}/s "
              f"speedup {speedup:.1f}x")
        assert speedup >= 2.0, (eager_rate, compiled_rate)
    finally:
        compiled.teardown()
