"""Object spilling + lineage reconstruction (reference:
raylet/local_object_manager.h:110 SpillObjects;
core_worker/object_recovery_manager.h:38 lineage rebuild)."""

import numpy as np
import pytest

import ray_tpu as rt
from ray_tpu.core.worker import global_worker


@pytest.fixture
def small_store_rt():
    # arena deliberately tiny so puts overflow to disk
    rt.init(num_cpus=2, _system_config={
        "object_store_memory_bytes": 8 * 1024 * 1024,
        "memory_store_threshold_bytes": 64 * 1024,
    })
    yield rt
    rt.shutdown()


@pytest.fixture
def normal_rt():
    rt.init(num_cpus=2, _system_config={
        "object_store_memory_bytes": 64 * 1024 * 1024,
    })
    yield rt
    rt.shutdown()


def test_put_overflow_spills_to_disk_and_reads_back(small_store_rt):
    # each array ~2 MB; an 8 MB arena cannot hold 8 of them + pins
    arrays = [np.full(256_000, i, np.float64) for i in range(8)]
    refs = [rt.put(a) for a in arrays]
    for i, ref in enumerate(refs):
        back = rt.get(ref, timeout=60)
        np.testing.assert_array_equal(back, arrays[i])


def test_spilled_object_usable_as_task_arg(small_store_rt):
    refs = [rt.put(np.full(256_000, i, np.float64)) for i in range(8)]

    @rt.remote
    def first(x):
        return float(x[0])

    vals = rt.get([first.remote(r) for r in refs], timeout=120)
    assert vals == [float(i) for i in range(8)]


def test_lineage_reconstruction_after_eviction(normal_rt):
    @rt.remote
    def make(i):
        return np.full(200_000, i, np.float64)  # shm-sized

    ref = make.remote(7)
    np.testing.assert_array_equal(rt.get(ref, timeout=60)[:3], 7.0)
    # simulate loss: delete the primary copy from the arena out-of-band
    store = global_worker.backend.object_plane.store
    key = ref.id().binary()
    assert store.contains(key)
    store.release(key)   # drop primary pin
    store.delete(key)
    assert not store.contains(key)
    # get() must re-execute make(7) via lineage, not raise ObjectLost
    back = rt.get(ref, timeout=120)
    np.testing.assert_array_equal(back[:3], 7.0)


def test_lineage_rebuilds_after_spill_file_lost(small_store_rt):
    """Delete a spilled primary's backing file out from under the store:
    rt.get must fall through arena-miss -> spill-miss -> ObjectLost and
    recover via try_reconstruct (re-running the creating task) instead of
    raising (ISSUE 14 satellite; previously only clean spill/read-back
    was covered)."""
    import os

    from ray_tpu.core.config import GlobalConfig
    from ray_tpu.runtime.object_plane import spill_file_path

    @rt.remote
    def make(i):
        return np.full(256_000, i, np.float64)  # ~2 MB, shm-sized

    # 8 x 2 MB into an 8 MB arena: overflow forces spills
    refs = [make.remote(i) for i in range(8)]
    vals = rt.get(refs, timeout=120)
    store = global_worker.backend.object_plane.store
    victim = None
    for i, ref in enumerate(refs):
        p = spill_file_path(GlobalConfig.session_dir, store.name,
                            ref.id().hex())
        if os.path.exists(p):
            victim = (i, ref, p)
            break
    assert victim is not None, "nothing spilled under memory pressure"
    i, ref, spill_path = victim
    os.unlink(spill_path)  # the disk copy is gone for good
    key = ref.id().binary()
    if store.contains(key):  # drop any arena copy too: total loss
        store.release(key)
        store.delete(key)
    del vals
    back = rt.get(ref, timeout=120)
    np.testing.assert_array_equal(back[:3], float(i))


def test_lineage_not_available_for_put_objects(normal_rt):
    arr = np.arange(200_000, dtype=np.float64)
    ref = rt.put(arr)
    store = global_worker.backend.object_plane.store
    key = ref.id().binary()
    rt.get(ref, timeout=30)
    store.release(key)
    store.delete(key)
    with pytest.raises(rt.exceptions.ObjectLostError):
        rt.get(ref, timeout=30)
