"""Multi-node protocol tests on one machine.

Uses the Cluster harness (ray_tpu/cluster_utils.py — role of reference
python/ray/cluster_utils.py:135): several node-daemon processes with
independent shm stores against one head, exercising cross-node object
transfer, node-death detection, and cross-node actor restart for real.
"""

import time

import numpy as np
import pytest

import ray_tpu as rt
from ray_tpu.cluster_utils import Cluster


@pytest.fixture()
def two_node_cluster():
    cluster = Cluster()
    cluster.add_node(num_cpus=2, resources={"nodeA": 1})
    cluster.add_node(num_cpus=2, resources={"nodeB": 1})
    rt.init(address=cluster.address, _system_config={
        "health_check_period_ms": 200,
        "health_check_timeout_ms": 1500,
    })
    yield cluster
    rt.shutdown()
    cluster.shutdown()


def test_cross_node_object_transfer(two_node_cluster):
    """A large result produced on node B is pulled to the driver's node."""

    @rt.remote(resources={"nodeB": 0.1})
    def make_big():
        return np.arange(400_000, dtype=np.float64)

    ref = make_big.remote()
    out = rt.get(ref, timeout=90)
    assert out.shape == (400_000,)
    assert float(out[-1]) == 399_999.0


def test_cross_node_ref_passing(two_node_cluster):
    """Object created on node A consumed by a task pinned to node B."""

    @rt.remote(resources={"nodeA": 0.1})
    def produce():
        return np.ones(300_000)

    @rt.remote(resources={"nodeB": 0.1})
    def consume(x):
        return float(x.sum())

    assert rt.get(consume.remote(produce.remote()), timeout=90) == 300_000.0


def test_scheduling_spreads_to_feasible_node(two_node_cluster):
    """A shape only node B can satisfy must land there."""

    @rt.remote(resources={"nodeB": 1})
    def where():
        return "B"

    assert rt.get(where.remote(), timeout=60) == "B"


def test_node_death_detected_and_actor_restarts(two_node_cluster):
    cluster = two_node_cluster

    @rt.remote(max_restarts=1)
    class Svc:
        def node_marker(self):
            # which custom resource this node advertises
            import os
            return os.getpid()

    # Pin the actor to node B via resources, then kill node B.
    @rt.remote(resources={"nodeB": 0.1}, max_restarts=0)
    class PinnedB:
        def ping(self):
            return "pong"

    a = PinnedB.remote()
    assert rt.get(a.ping.remote(), timeout=60) == "pong"

    node_b = cluster.nodes[1]
    cluster.remove_node(node_b)  # SIGKILL: daemon + its workers die

    # head health checker must mark the node dead
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        alive = [n for n in rt.nodes() if n["Alive"]]
        if len(alive) == 1:
            break
        time.sleep(0.1)
    else:
        pytest.fail("head never marked the killed node dead")

    # the pinned actor died with its node
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        try:
            rt.get(a.ping.remote(), timeout=10)
            time.sleep(0.2)
        except rt.exceptions.ActorError:
            break
    else:
        pytest.fail("actor on dead node kept answering")

    # unpinned tasks keep working on the surviving node
    @rt.remote
    def alive_check():
        return 1

    assert rt.get(alive_check.remote(), timeout=60) == 1
