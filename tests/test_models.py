"""Model-family tests on the virtual 8-device CPU mesh."""

import dataclasses
import functools

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ray_tpu.models import llama
from ray_tpu.models.mlp import MLPConfig, mlp_init, mlp_apply, mlp_loss
from ray_tpu.parallel import MeshSpec, build_mesh


def make_inputs(cfg, B=2, L=32, seed=0):
    return jax.random.randint(jax.random.PRNGKey(seed), (B, L), 0,
                              cfg.vocab_size)


class TestLlamaSingleDevice:
    def test_forward_shape_and_finite(self):
        cfg = llama.LlamaConfig.tiny(dtype=jnp.float32)
        params = llama.init_params(cfg, jax.random.PRNGKey(0))
        tokens = make_inputs(cfg)
        logits = jax.jit(functools.partial(llama.forward, cfg=cfg))(
            params, tokens)
        assert logits.shape == (2, 32, cfg.vocab_size)
        assert np.isfinite(np.asarray(logits)).all()

    def test_loss_decreases_with_sgd(self):
        cfg = llama.LlamaConfig.tiny(n_layers=2, dtype=jnp.float32)
        params = llama.init_params(cfg, jax.random.PRNGKey(0))
        tokens = make_inputs(cfg, B=4, L=16)
        loss_grad = jax.jit(jax.value_and_grad(
            functools.partial(llama.loss_fn, cfg=cfg)))
        l0, g = loss_grad(params, tokens)
        params2 = jax.tree.map(lambda p, gi: p - 0.5 * gi, params, g)
        l1, _ = loss_grad(params2, tokens)
        assert float(l1) < float(l0)

    def test_param_specs_align(self):
        cfg = llama.LlamaConfig.tiny()
        params = llama.init_params(cfg, jax.random.PRNGKey(0))
        specs = llama.param_specs(cfg)
        jax.tree.map(lambda p, s: None, params, specs)  # same structure
        # every leaf rank matches its spec length
        flat_p = jax.tree.leaves(params)
        flat_s = jax.tree.leaves(specs, is_leaf=lambda x: isinstance(x, P))
        for p, s in zip(flat_p, flat_s):
            assert len(s) <= p.ndim


class TestLlamaSharded:
    @pytest.fixture(scope="class")
    def mesh(self):
        return build_mesh(MeshSpec(dp=2, fsdp=2, tp=2))

    def _sharded_forward(self, cfg, mesh, B=4, L=32):
        params = llama.init_params(cfg, jax.random.PRNGKey(0))
        specs = llama.param_specs(cfg)
        params = jax.device_put(
            params, jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                                 is_leaf=lambda x: isinstance(x, P)))
        tokens = jax.device_put(
            make_inputs(cfg, B, L),
            NamedSharding(mesh, P(("dp", "fsdp"), None)))
        out = jax.jit(functools.partial(llama.forward, cfg=cfg, mesh=mesh))(
            params, tokens)
        return params, tokens, out

    def test_fsdp_tp_forward_matches_single(self, mesh):
        cfg = llama.LlamaConfig.tiny(dtype=jnp.float32)
        params, tokens, out = self._sharded_forward(cfg, mesh)
        expect = jax.jit(functools.partial(llama.forward, cfg=cfg))(
            jax.device_put(jax.tree.map(np.asarray, params)),
            np.asarray(tokens))
        np.testing.assert_allclose(np.asarray(out), np.asarray(expect),
                                   rtol=1e-4, atol=1e-4)

    def test_ring_attention_matches_full(self):
        mesh = build_mesh(MeshSpec(sp=4, tp=2))
        cfg_full = llama.LlamaConfig.tiny(dtype=jnp.float32)
        cfg_ring = llama.LlamaConfig.tiny(dtype=jnp.float32,
                                          attention="ring")
        params = llama.init_params(cfg_full, jax.random.PRNGKey(0))
        tokens = make_inputs(cfg_full, B=2, L=32)
        full = jax.jit(functools.partial(llama.forward, cfg=cfg_full))(
            params, tokens)
        ring = jax.jit(functools.partial(llama.forward, cfg=cfg_ring,
                                         mesh=mesh))(params, tokens)
        np.testing.assert_allclose(np.asarray(ring), np.asarray(full),
                                   rtol=2e-3, atol=2e-3)

    def test_ulysses_attention_matches_full(self):
        mesh = build_mesh(MeshSpec(sp=4, tp=2))
        cfg_full = llama.LlamaConfig.tiny(dtype=jnp.float32)
        cfg_uly = llama.LlamaConfig.tiny(dtype=jnp.float32,
                                         attention="ulysses")
        params = llama.init_params(cfg_full, jax.random.PRNGKey(0))
        tokens = make_inputs(cfg_full, B=2, L=32)
        full = jax.jit(functools.partial(llama.forward, cfg=cfg_full))(
            params, tokens)
        uly = jax.jit(functools.partial(llama.forward, cfg=cfg_uly,
                                        mesh=mesh))(params, tokens)
        np.testing.assert_allclose(np.asarray(uly), np.asarray(full),
                                   rtol=2e-3, atol=2e-3)

    def test_flash_falls_back_on_cpu_mesh(self):
        # attention='flash' on a CPU mesh routes to the blockwise fallback
        # (Mosaic kernels only lower on real TPU) and matches full attention.
        mesh = build_mesh(MeshSpec(dp=2, fsdp=2, tp=2))
        cfg_full = llama.LlamaConfig.tiny(dtype=jnp.float32, n_layers=2)
        cfg_fl = llama.LlamaConfig.tiny(dtype=jnp.float32, n_layers=2,
                                        attention="flash")
        params = llama.init_params(cfg_full, jax.random.PRNGKey(0))
        tokens = make_inputs(cfg_full, B=4, L=32)
        full = jax.jit(functools.partial(llama.forward, cfg=cfg_full))(
            params, tokens)
        fl = jax.jit(functools.partial(llama.forward, cfg=cfg_fl,
                                       mesh=mesh))(params, tokens)
        np.testing.assert_allclose(np.asarray(fl), np.asarray(full),
                                   rtol=2e-3, atol=2e-3)

    def test_ring_loss_with_pow2_seq(self):
        # loss_fn must keep the full (sp-divisible) seq through forward.
        mesh = build_mesh(MeshSpec(sp=4, tp=2))
        cfg = llama.LlamaConfig.tiny(dtype=jnp.float32, n_layers=2,
                                     attention="ring")
        params = llama.init_params(cfg, jax.random.PRNGKey(0))
        tokens = make_inputs(cfg, B=2, L=32)
        loss = jax.jit(functools.partial(llama.loss_fn, cfg=cfg,
                                         mesh=mesh))(params, tokens)
        assert np.isfinite(float(loss))

    def test_pipeline_forward_matches_single(self):
        mesh = build_mesh(MeshSpec(pp=2, dp=2, tp=2))
        cfg = llama.LlamaConfig.tiny(dtype=jnp.float32, pp_microbatches=2)
        params = llama.init_params(cfg, jax.random.PRNGKey(0))
        specs = llama.param_specs(cfg)
        sharded = jax.device_put(
            params, jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                                 is_leaf=lambda x: isinstance(x, P)))
        tokens = make_inputs(cfg, B=4, L=16)
        expect = jax.jit(functools.partial(llama.forward, cfg=cfg))(
            params, tokens)
        got = jax.jit(functools.partial(llama.forward, cfg=cfg, mesh=mesh))(
            sharded, tokens)
        np.testing.assert_allclose(np.asarray(got), np.asarray(expect),
                                   rtol=1e-4, atol=1e-4)

    def test_pipeline_grads(self):
        """Pipelined grads must MATCH the single-program reference, not just
        be finite — catches shard_map transpose bugs that scale grads by the
        axis size (check_rep is disabled in shard_map_compat)."""
        mesh = build_mesh(MeshSpec(pp=2, fsdp=2, tp=2))
        cfg = llama.LlamaConfig.tiny(dtype=jnp.float32, n_layers=2,
                                     pp_microbatches=2)
        params = llama.init_params(cfg, jax.random.PRNGKey(0))
        tokens = make_inputs(cfg, B=4, L=16)
        g = jax.jit(jax.grad(functools.partial(
            llama.loss_fn, cfg=cfg, mesh=mesh)))(params, tokens)
        g_ref = jax.jit(jax.grad(functools.partial(
            llama.loss_fn, cfg=cfg, mesh=None)))(params, tokens)
        for got, ref in zip(jax.tree.leaves(g), jax.tree.leaves(g_ref)):
            np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                       rtol=1e-4, atol=1e-5)


class TestMLP:
    def test_train_step_decreases_loss(self):
        cfg = MLPConfig(in_dim=16, hidden=32, out_dim=4)
        params = mlp_init(cfg, jax.random.PRNGKey(0))
        x = jax.random.normal(jax.random.PRNGKey(1), (64, 16))
        y = jax.random.randint(jax.random.PRNGKey(2), (64,), 0, 4)
        lg = jax.jit(jax.value_and_grad(mlp_loss))
        l0, g = lg(params, (x, y))
        params = jax.tree.map(lambda p, gi: p - 0.1 * gi, params, g)
        l1, _ = lg(params, (x, y))
        assert float(l1) < float(l0)


class TestRematPolicies:
    """remat_policy must be a pure speed/memory lever: every policy
    computes identical losses AND gradients (ISSUE 7 parity guard)."""

    def _loss_and_grads(self, policy):
        cfg = llama.LlamaConfig.tiny(n_layers=2, dtype=jnp.float32,
                                     remat_policy=policy)
        params = llama.init_params(cfg, jax.random.PRNGKey(0))
        tokens = make_inputs(cfg, B=2, L=16)
        loss, grads = jax.jit(jax.value_and_grad(
            functools.partial(llama.loss_fn, cfg=cfg)))(params, tokens)
        return float(loss), grads

    def test_policies_identical_loss_and_grads(self):
        ref_loss, ref_grads = self._loss_and_grads("full")
        for policy in ("dots", "selective"):
            loss, grads = self._loss_and_grads(policy)
            assert loss == pytest.approx(ref_loss, abs=1e-6), policy
            for got, ref in zip(jax.tree.leaves(grads),
                                jax.tree.leaves(ref_grads)):
                np.testing.assert_allclose(
                    np.asarray(got), np.asarray(ref),
                    rtol=1e-5, atol=1e-6, err_msg=policy)

    def test_unknown_policy_raises(self):
        with pytest.raises(ValueError, match="remat_policy"):
            llama.remat_policy_fn("nope")


class TestFsdpOverlap:
    """Explicit prefetch-scheduled fsdp step vs the GSPMD-auto step:
    same loss, same grads — the overlap schedule only moves collectives,
    never the math (ISSUE 7 numeric-parity acceptance)."""

    @pytest.fixture(scope="class")
    def mesh(self):
        return build_mesh(MeshSpec(dp=2, fsdp=4))

    def _place(self, cfg, mesh, B=8, L=16):
        params = llama.init_params(cfg, jax.random.PRNGKey(0))
        specs = llama.param_specs(cfg)
        params = jax.device_put(
            params, jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                                 is_leaf=lambda x: isinstance(x, P)))
        tokens = jax.device_put(
            make_inputs(cfg, B, L),
            NamedSharding(mesh, P(("dp", "fsdp"), None)))
        return params, tokens

    def test_overlap_loss_and_grads_match_gspmd(self, mesh):
        cfg = llama.LlamaConfig.tiny(n_layers=2, dtype=jnp.float32)
        params, tokens = self._place(cfg, mesh)
        cfg_ov = dataclasses.replace(cfg, fsdp_overlap=True)
        vag = lambda c: jax.jit(jax.value_and_grad(functools.partial(
            llama.loss_fn, cfg=c, mesh=mesh)))
        l_ref, g_ref = vag(cfg)(params, tokens)
        l_ov, g_ov = vag(cfg_ov)(params, tokens)
        assert float(l_ov) == pytest.approx(float(l_ref), abs=1e-5)
        for got, ref in zip(jax.tree.leaves(g_ov), jax.tree.leaves(g_ref)):
            np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                       rtol=1e-4, atol=1e-5)

    def test_overlap_composes_with_selective_remat(self, mesh):
        cfg = llama.LlamaConfig.tiny(n_layers=2, dtype=jnp.float32,
                                     remat_policy="selective")
        params, tokens = self._place(cfg, mesh)
        cfg_ov = dataclasses.replace(cfg, fsdp_overlap=True)
        l_ref = jax.jit(functools.partial(
            llama.loss_fn, cfg=cfg, mesh=mesh))(params, tokens)
        l_ov = jax.jit(functools.partial(
            llama.loss_fn, cfg=cfg_ov, mesh=mesh))(params, tokens)
        assert float(l_ov) == pytest.approx(float(l_ref), abs=1e-5)

    def test_overlap_rejects_tp_sharding(self):
        mesh = build_mesh(MeshSpec(fsdp=2, tp=2, dp=2))
        cfg = llama.LlamaConfig.tiny(n_layers=2, dtype=jnp.float32,
                                     fsdp_overlap=True)
        params, tokens = self._place(cfg, mesh, B=4)
        with pytest.raises(ValueError, match="fsdp_overlap"):
            jax.jit(functools.partial(
                llama.loss_fn, cfg=cfg, mesh=mesh))(params, tokens)

    def test_overlap_noop_when_fsdp_unsharded(self):
        # fsdp=1 mesh: the flag must route to the normal GSPMD path
        mesh = build_mesh(MeshSpec(dp=8))
        cfg = llama.LlamaConfig.tiny(n_layers=2, dtype=jnp.float32,
                                     fsdp_overlap=True)
        params, tokens = self._place(cfg, mesh)
        loss = jax.jit(functools.partial(
            llama.loss_fn, cfg=cfg, mesh=mesh))(params, tokens)
        assert np.isfinite(float(loss))


class TestInt8MLP:
    def test_int8_flag_changes_path_but_stays_finite(self):
        cfg = llama.LlamaConfig.tiny(n_layers=2, dtype=jnp.float32)
        cfg8 = dataclasses.replace(cfg, int8_mlp=True)
        params = llama.init_params(cfg, jax.random.PRNGKey(0))
        tokens = make_inputs(cfg, B=2, L=16)
        l_fp, g_fp = jax.jit(jax.value_and_grad(functools.partial(
            llama.loss_fn, cfg=cfg)))(params, tokens)
        l_8, g_8 = jax.jit(jax.value_and_grad(functools.partial(
            llama.loss_fn, cfg=cfg8)))(params, tokens)
        assert np.isfinite(float(l_8))
        assert all(np.isfinite(np.asarray(g)).all()
                   for g in jax.tree.leaves(g_8))
        # quantized path is close to fp (W8A8 dynamic quant, tiny model)
        assert float(l_8) == pytest.approx(float(l_fp), rel=0.05)
