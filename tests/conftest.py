"""Test fixtures.

Mirrors the reference's conftest design (reference:
python/ray/tests/conftest.py:532 ray_start_regular, :479 _ray_start):
fixtures boot/teardown runtimes per test; JAX tests run on a virtual
8-device CPU mesh (the reference's fake-multi-node trick applied to chips —
SURVEY.md §4 item (d)).
"""

import os

# The suite runs on a virtual 8-device CPU mesh. The ambient sandbox pins
# the real-TPU platform via sitecustomize (env vars alone don't stick), so
# override at the jax.config level before any backend initializes.
os.environ["JAX_PLATFORMS"] = "cpu"
if "--xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running tests excluded from the tier-1 "
        "`-m 'not slow'` sweep")
    config.addinivalue_line(
        "markers", "pallas_interpret: Pallas TPU kernel tests that run "
        "in interpret mode on the tier-1 CPU sweep (JAX_PLATFORMS=cpu) "
        "— same kernel logic, emulated lowering")
    config.addinivalue_line(
        "markers", "chaos: fault-injection lifecycle tests driven via "
        "ray_tpu.util.fault_injector (RTPU_FAULT_INJECT hook points)")


@pytest.fixture
def fault_injector():
    """Armed-and-disarmed FaultInjector access: yields the module, then
    resets the point table and env var in teardown so chaos specs never
    leak into the next test."""
    from ray_tpu.util import fault_injector as fi
    yield fi
    fi.reset()
    os.environ.pop(fi.ENV_VAR, None)


@pytest.fixture
def pallas_interpret():
    """Interpret flag for Pallas kernel tests: True off-TPU (tier-1 runs
    the kernels via the Pallas interpreter on CPU), False on real TPU
    where the compiled kernel itself should be exercised."""
    return jax.default_backend() != "tpu"


@pytest.fixture
def rtpu_local():
    import ray_tpu
    ray_tpu.init(local_mode=True, num_cpus=4)
    yield ray_tpu
    ray_tpu.shutdown()


@pytest.fixture
def rtpu_cluster():
    import ray_tpu
    ray_tpu.init(num_cpus=2, _system_config={
        "object_store_memory_bytes": 256 * 1024 * 1024,
        "worker_pool_max": 4,
    })
    yield ray_tpu
    ray_tpu.shutdown()


@pytest.fixture(scope="session")
def cpu_mesh_devices():
    import jax
    devices = jax.devices("cpu")
    assert len(devices) >= 8, "conftest must provide 8 virtual devices"
    return devices
