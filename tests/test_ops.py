"""Pallas kernel tests — run in interpret mode on the CPU mesh
(the kernels themselves are exercised on real TPU by bench.py)."""

import functools

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ray_tpu.ops import blockwise_attention, flash_attention
from ray_tpu.parallel.attention import causal_attention


def make_qkv(B=2, L=256, H=4, D=32, dtype=jnp.float32, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    return tuple(jax.random.normal(k, (B, L, H, D), dtype) for k in ks)


class TestBlockwiseAttention:
    def test_matches_naive(self):
        q, k, v = make_qkv()
        ref = causal_attention(q, k, v).astype(jnp.float32)
        got = blockwise_attention(q, k, v, block_k=64).astype(jnp.float32)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=2e-4, atol=2e-4)

    def test_grads_match_naive(self):
        q, k, v = make_qkv(L=128)

        def loss_ref(q, k, v):
            return (causal_attention(q, k, v) ** 2).sum()

        def loss_blk(q, k, v):
            return (blockwise_attention(q, k, v, block_k=32)
                    .astype(jnp.float32) ** 2).sum()

        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        gb = jax.grad(loss_blk, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gr, gb):
            np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                       rtol=1e-3, atol=1e-3)


class TestFlashAttention:
    """interpret=True executes the actual kernel logic on CPU."""

    def test_fwd_matches_naive(self):
        q, k, v = make_qkv(L=256)
        ref = causal_attention(q, k, v).astype(jnp.float32)
        got = flash_attention(q, k, v, True, None, 128, 128, True)
        np.testing.assert_allclose(np.asarray(got, np.float32),
                                   np.asarray(ref), rtol=2e-4, atol=2e-4)

    def test_bwd_matches_naive(self):
        q, k, v = make_qkv(L=128, H=2)

        def loss_ref(q, k, v):
            return (causal_attention(q, k, v) ** 2).sum()

        def loss_fl(q, k, v):
            return (flash_attention(q, k, v, True, None, 64, 64, True)
                    .astype(jnp.float32) ** 2).sum()

        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        gf = jax.grad(loss_fl, argnums=(0, 1, 2))(q, k, v)
        for name, a, b in zip("qkv", gr, gf):
            np.testing.assert_allclose(
                np.asarray(b), np.asarray(a), rtol=2e-3, atol=2e-3,
                err_msg=f"d{name} mismatch")

    def test_noncausal(self):
        q, k, v = make_qkv(L=128)
        s = jnp.einsum("bqhd,bkhd->bhqk", q * q.shape[-1] ** -0.5, k)
        p = jax.nn.softmax(s, axis=-1)
        ref = jnp.einsum("bhqk,bkhd->bqhd", p, v)
        got = flash_attention(q, k, v, False, None, 64, 64, True)
        np.testing.assert_allclose(np.asarray(got, np.float32),
                                   np.asarray(ref, np.float32),
                                   rtol=2e-4, atol=2e-4)

    def test_llama_flash_matches_full(self):
        from ray_tpu.models import llama
        cfg_full = llama.LlamaConfig.tiny(dtype=jnp.float32, n_layers=2)
        params = llama.init_params(cfg_full, jax.random.PRNGKey(0))
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0,
                                    cfg_full.vocab_size)
        full = llama.forward(params, tokens, cfg_full)
        # route through the blockwise fallback semantics via flash interpret
        import ray_tpu.ops as ops
        orig = ops.flash_attention
        try:
            def interp_flash(q, k, v, *a, **kw):
                return orig(q, k, v, True, None, 16, 16, True)
            ops.flash_attention = interp_flash
            cfg_fl = llama.LlamaConfig.tiny(dtype=jnp.float32, n_layers=2,
                                            attention="flash")
            fl = llama.forward(params, tokens, cfg_fl)
        finally:
            ops.flash_attention = orig
        np.testing.assert_allclose(np.asarray(fl), np.asarray(full),
                                   rtol=2e-3, atol=2e-3)
