"""Pallas kernel tests — run in interpret mode on the CPU mesh
(the kernels themselves are exercised on real TPU by bench.py)."""

import functools

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ray_tpu.ops import blockwise_attention, flash_attention
from ray_tpu.parallel.attention import causal_attention


def make_qkv(B=2, L=256, H=4, D=32, dtype=jnp.float32, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    return tuple(jax.random.normal(k, (B, L, H, D), dtype) for k in ks)


class TestBlockwiseAttention:
    def test_matches_naive(self):
        q, k, v = make_qkv()
        ref = causal_attention(q, k, v).astype(jnp.float32)
        got = blockwise_attention(q, k, v, block_k=64).astype(jnp.float32)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=2e-4, atol=2e-4)

    def test_grads_match_naive(self):
        q, k, v = make_qkv(L=128)

        def loss_ref(q, k, v):
            return (causal_attention(q, k, v) ** 2).sum()

        def loss_blk(q, k, v):
            return (blockwise_attention(q, k, v, block_k=32)
                    .astype(jnp.float32) ** 2).sum()

        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        gb = jax.grad(loss_blk, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gr, gb):
            np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                       rtol=1e-3, atol=1e-3)


class TestFlashAttention:
    """interpret=True executes the actual kernel logic on CPU."""

    def test_fwd_matches_naive(self):
        q, k, v = make_qkv(L=256)
        ref = causal_attention(q, k, v).astype(jnp.float32)
        got = flash_attention(q, k, v, True, None, 128, 128, True)
        np.testing.assert_allclose(np.asarray(got, np.float32),
                                   np.asarray(ref), rtol=2e-4, atol=2e-4)

    def test_bwd_matches_naive(self):
        q, k, v = make_qkv(L=128, H=2)

        def loss_ref(q, k, v):
            return (causal_attention(q, k, v) ** 2).sum()

        def loss_fl(q, k, v):
            return (flash_attention(q, k, v, True, None, 64, 64, True)
                    .astype(jnp.float32) ** 2).sum()

        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        gf = jax.grad(loss_fl, argnums=(0, 1, 2))(q, k, v)
        for name, a, b in zip("qkv", gr, gf):
            np.testing.assert_allclose(
                np.asarray(b), np.asarray(a), rtol=2e-3, atol=2e-3,
                err_msg=f"d{name} mismatch")

    def test_noncausal(self):
        q, k, v = make_qkv(L=128)
        s = jnp.einsum("bqhd,bkhd->bhqk", q * q.shape[-1] ** -0.5, k)
        p = jax.nn.softmax(s, axis=-1)
        ref = jnp.einsum("bhqk,bkhd->bqhd", p, v)
        got = flash_attention(q, k, v, False, None, 64, 64, True)
        np.testing.assert_allclose(np.asarray(got, np.float32),
                                   np.asarray(ref, np.float32),
                                   rtol=2e-4, atol=2e-4)

    def test_llama_flash_matches_full(self):
        from ray_tpu.models import llama
        cfg_full = llama.LlamaConfig.tiny(dtype=jnp.float32, n_layers=2)
        params = llama.init_params(cfg_full, jax.random.PRNGKey(0))
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0,
                                    cfg_full.vocab_size)
        full = llama.forward(params, tokens, cfg_full)
        # route through the blockwise fallback semantics via flash interpret
        import ray_tpu.ops as ops
        orig = ops.flash_attention
        try:
            def interp_flash(q, k, v, *a, **kw):
                return orig(q, k, v, True, None, 16, 16, True)
            ops.flash_attention = interp_flash
            cfg_fl = llama.LlamaConfig.tiny(dtype=jnp.float32, n_layers=2,
                                            attention="flash")
            fl = llama.forward(params, tokens, cfg_fl)
        finally:
            ops.flash_attention = orig
        np.testing.assert_allclose(np.asarray(fl), np.asarray(full),
                                   rtol=2e-3, atol=2e-3)


class TestBlockAutotune:
    @pytest.fixture(autouse=True)
    def _clean_cache(self):
        import importlib
        fa = importlib.import_module("ray_tpu.ops.flash_attention")
        fa.clear_block_cache()
        yield
        fa.clear_block_cache()

    def test_pick_block_floor(self):
        import importlib
        pick_block = importlib.import_module(
            "ray_tpu.ops.flash_attention").pick_block
        assert pick_block(256) == 256
        assert pick_block(20) is None        # no divisor >= 8
        assert pick_block(4) is None         # below the Mosaic floor
        assert pick_block(4, min_block=1) == 4   # interpret-only escape

    def test_candidates_respect_floor_and_divisibility(self):
        import importlib
        block_candidates = importlib.import_module(
            "ray_tpu.ops.flash_attention").block_candidates
        cands = block_candidates(2048, 2048, 64)
        assert cands, "L=2048 must have candidates"
        assert cands[0] == (256, 256)        # heuristic-best first
        for bq, bk in cands:
            assert bq >= 8 and bk >= 8
            assert 2048 % bq == 0 and 2048 % bk == 0

    def test_autotune_measures_and_caches(self, monkeypatch):
        import importlib
        fa = importlib.import_module("ray_tpu.ops.flash_attention")
        calls = []

        def fake_timer(Lq, Lk, D, dtype, bq, bk, **kw):
            calls.append((bq, bk))
            return abs(bq - 64) + abs(bk - 32)   # makes (64, 32) win

        monkeypatch.setattr(fa, "_time_blocks", fake_timer)
        best = fa.autotune_blocks(128, 64, 32, jnp.float32, measure=True)
        assert best == (64, 32)
        assert calls, "measure=True must actually time candidates"
        assert fa.get_tuned_blocks(128, 64, 32, jnp.float32) == (64, 32)
        # second call is a pure cache hit: no further timing
        n = len(calls)
        assert fa.autotune_blocks(128, 64, 32, jnp.float32,
                                  measure=True) == (64, 32)
        assert len(calls) == n

    def test_autotune_heuristic_without_measure(self):
        import importlib
        fa = importlib.import_module("ray_tpu.ops.flash_attention")
        assert fa.autotune_blocks(2048, 2048, 64, jnp.bfloat16,
                                  measure=False) == (256, 256)

    def test_autotune_indivisible_returns_none(self):
        import importlib
        fa = importlib.import_module("ray_tpu.ops.flash_attention")
        assert fa.autotune_blocks(20, 20, 64, jnp.float32,
                                  measure=False) is None

    def test_flash_attention_uses_tuned_blocks(self, monkeypatch):
        """blk_q/blk_k=None routes through the tuned cache (the sharded
        wrappers pass None so every trace picks the autotuned block)."""
        import importlib
        fa = importlib.import_module("ray_tpu.ops.flash_attention")
        q, k, v = make_qkv(B=1, L=64, H=2, D=32)
        fa._BLOCK_CACHE[fa._block_cache_key(64, 64, 32, q.dtype)] = (32, 32)
        seen = {}
        real = fa._fwd_call

        def spy(q_, k_, v_, causal, scale, blk_q, blk_k, interpret):
            seen["blocks"] = (blk_q, blk_k)
            return real(q_, k_, v_, causal, scale, blk_q, blk_k, interpret)

        monkeypatch.setattr(fa, "_fwd_call", spy)
        fa.flash_attention(q, k, v, blk_q=None, blk_k=None, interpret=True)
        assert seen["blocks"] == (32, 32)


class TestInt8Matmul:
    def test_forward_close_to_fp(self):
        from ray_tpu.ops import int8_matmul
        x = jax.random.normal(jax.random.PRNGKey(0), (64, 128))
        w = jax.random.normal(jax.random.PRNGKey(1), (128, 32))
        got = np.asarray(int8_matmul(x, w))
        ref = np.asarray(x @ w)
        rel = np.abs(got - ref).max() / np.abs(ref).max()
        assert rel < 0.02, rel    # dynamic W8A8: ~1% at these shapes

    def test_grads_are_exact_fp_transpose(self):
        """The straight-through backward uses fp transposes of the ORIGINAL
        operands, so grads equal the fp matmul's grads exactly."""
        from ray_tpu.ops import int8_matmul
        x = jax.random.normal(jax.random.PRNGKey(2), (16, 32))
        w = jax.random.normal(jax.random.PRNGKey(3), (32, 8))
        g = jax.random.normal(jax.random.PRNGKey(4), (16, 8))
        loss8 = lambda x, w: (int8_matmul(x, w) * g).sum()
        lossfp = lambda x, w: ((x @ w) * g).sum()
        gx8, gw8 = jax.grad(loss8, argnums=(0, 1))(x, w)
        gxf, gwf = jax.grad(lossfp, argnums=(0, 1))(x, w)
        np.testing.assert_allclose(np.asarray(gx8), np.asarray(gxf),
                                   rtol=1e-6, atol=1e-6)
        np.testing.assert_allclose(np.asarray(gw8), np.asarray(gwf),
                                   rtol=1e-6, atol=1e-6)

    def test_jit_and_finite(self):
        from ray_tpu.ops import int8_matmul
        x = jax.random.normal(jax.random.PRNGKey(5), (8, 16))
        w = jax.random.normal(jax.random.PRNGKey(6), (16, 4))
        out = jax.jit(int8_matmul)(x, w)
        assert out.shape == (8, 4)
        assert np.isfinite(np.asarray(out)).all()
