"""Cluster-wide sampling profiler plane.

Units: collapsed-stack folding, bounded-table overflow with EXACT drop
counts, burst-capture determinism under a synthetic busy thread,
self/cum frame attribution (recursion deduped), speedscope export, and
the head-side ProfileStore (rings, LRU, filters).

E2E: a two-node cluster where continuous profiles from the head, both
node daemons, workers and the driver all land in the head's store via
telemetry_push, tagged with node/worker identity; the `profile` CLI
renders them (table, --flame, --speedscope JSON) and --record fans a
burst out cluster-wide through profiles_record.

Reference: `ray stack` / py-spy's dashboard profile_manager — ours is
continuous + cluster-aggregated rather than one-shot per-process.
"""

import io
import json
import subprocess
import sys
import threading
import time
from contextlib import redirect_stdout

import pytest

from ray_tpu.util import stack_profiler as sp

MiB = 1 << 20


# ----------------------------------------------------------------- units

def test_profiler_imports_without_jax():
    """Tier-1 contract: the profiler runs inside the head and node
    daemons, which must never pull in the accelerator stack."""
    code = (
        "import sys; from ray_tpu.util import stack_profiler as sp; "
        "e = sp.burst_capture(0.05, hz=50); "
        "assert e['samples'] >= 0, e; "
        "p = sp.StackProfiler(hz=50); p.start(); p.stop(); "
        "print('jax' in sys.modules)")
    out = subprocess.run([sys.executable, "-c", code],
                         capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr
    assert out.stdout.strip() == "False", out.stdout


def test_fold_frame_root_first():
    """Collapsed stacks are root-first mod.fn:line joined by ';' —
    the flamegraph.pl contract."""
    marker = {}

    def inner():
        marker["folded"] = sp._fold_frame(sys._getframe())

    def outer():
        inner()

    outer()
    folded = marker["folded"]
    frames = folded.split(";")
    mod = __name__  # tests.test_stack_profiler
    i_outer = next(i for i, f in enumerate(frames)
                   if f.startswith(f"{mod}.outer:"))
    i_inner = next(i for i, f in enumerate(frames)
                   if f.startswith(f"{mod}.inner:"))
    assert i_outer < i_inner  # root-first: caller before callee
    assert frames[-1].startswith(f"{mod}.inner:")  # leaf is last
    for f in frames:
        name, _, line = f.rpartition(":")
        assert name and line.isdigit(), f


def _park(fn_event):
    fn_event.wait()


def test_table_overflow_drop_counts_exact():
    """A full fold table drops samples on UNSEEN stacks and counts every
    drop exactly, so the profile's denominator stays honest."""
    release = threading.Event()

    # six threads parked in six distinct functions -> six distinct stacks
    parked = []
    ns = {}
    for i in range(6):
        exec(f"def park_{i}(ev):\n    ev.wait()\n", ns)  # distinct frames
        t = threading.Thread(target=ns[f"park_{i}"], args=(release,),
                             daemon=True)
        t.start()
        parked.append(t)
    try:
        time.sleep(0.1)  # let all six reach the wait
        ours = {t.ident for t in parked}
        # sample ONLY the six parked threads: skip every other live
        # thread (pytest main, any runtime background threads)
        skip = frozenset(tid for tid in sys._current_frames()
                         if tid not in ours)
        table = {}
        taken, dropped = sp._sample_once(table, 4, skip)
        assert taken == 6
        assert len(table) == 4
        assert dropped == 2  # exactly the two that didn't fit
        # second pass: the 4 resident stacks increment, same 2 drop again
        taken2, dropped2 = sp._sample_once(table, 4, skip)
        assert taken2 == 6 and dropped2 == 2
        assert sorted(table.values()) == [2, 2, 2, 2]
    finally:
        release.set()
        for t in parked:
            t.join(timeout=5)


def test_burst_capture_sees_busy_thread():
    """Burst mode must attribute a synthetic busy loop to its function,
    and samples == sum(stack counts) + dropped (no sample unaccounted)."""
    stop = threading.Event()

    def spin_hot():
        while not stop.is_set():
            sum(i * i for i in range(500))

    t = threading.Thread(target=spin_hot, daemon=True, name="spin-hot")
    t.start()
    try:
        e = sp.burst_capture(0.5, hz=199.0)
    finally:
        stop.set()
        t.join(timeout=5)
    assert e["burst"] is True and e["samples"] > 0
    assert sum(e["stacks"].values()) + e["dropped"] == e["samples"]
    assert 0.3 <= e["window_s"] <= 2.0
    hot = [s for s in e["stacks"] if "spin_hot" in s]
    assert hot, list(e["stacks"])[:5]
    # the busy thread is caught on (nearly) every sampling pass: its
    # stacks' combined count rivals the most-sampled parked thread.
    # (Do NOT assert top-N membership — leftover daemon threads from
    # earlier test modules park on a single line and each earn a full
    # per-pass count, while spin_hot's samples spread over several
    # line numbers, so rank alone is order-of-collection fragile.)
    hot_total = sum(e["stacks"][s] for s in hot)
    assert hot_total >= 0.5 * max(e["stacks"].values()), (
        hot_total, sorted(e["stacks"].items(), key=lambda kv: -kv[1])[:5])
    # and top_frames over only the busy thread's stacks names the loop
    top = sp.top_frames({s: e["stacks"][s] for s in hot}, 3)
    assert any("spin_hot" in r["frame"] or "<genexpr>" in r["frame"]
               for r in top), top


def test_continuous_profiler_export_drains_atomically():
    p = sp.StackProfiler(hz=100.0)
    p.start()
    try:
        time.sleep(0.4)
        first = p.export()
        assert first is not None and first["samples"] > 0
        assert sum(first["stacks"].values()) + first["dropped"] \
            == first["samples"]
        # the drain reset the window: an immediate re-export is empty-ish
        again = p.export()
        assert again is None or again["samples"] < first["samples"]
    finally:
        p.stop()
    assert not p.running


def test_top_frames_self_cum_recursion_dedup():
    stacks = {"a;b;c": 3, "a;b": 2, "a;a;a": 5}
    rows = {r["frame"]: r for r in sp.top_frames(stacks, 0)}
    assert rows["c"]["self"] == 3 and rows["c"]["cum"] == 3
    assert rows["b"]["self"] == 2 and rows["b"]["cum"] == 5
    # recursion: 'a' appears 3x in one stack but its 5 samples count ONCE
    assert rows["a"]["self"] == 5 and rows["a"]["cum"] == 10
    # sorted by self desc
    ordered = sp.top_frames(stacks, 2)
    assert [r["frame"] for r in ordered] == ["a", "c"]


def test_speedscope_export_schema():
    stacks = {"m.f:1;m.g:2": 4, "m.f:1": 6}
    ss = sp.to_speedscope(stacks, name="unit")
    assert ss["$schema"] == \
        "https://www.speedscope.app/file-format-schema.json"
    frames = ss["shared"]["frames"]
    prof = ss["profiles"][0]
    assert prof["type"] == "sampled" and prof["name"] == "unit"
    assert prof["endValue"] == sum(prof["weights"]) == 10
    assert len(prof["samples"]) == len(prof["weights"]) == 2
    for row in prof["samples"]:
        assert all(0 <= ix < len(frames) for ix in row)
    # frame interning: m.f:1 appears in both stacks but is stored once
    assert sum(1 for f in frames if f["name"] == "m.f:1") == 1
    json.dumps(ss)  # must be JSON-serializable as-is


def test_profile_store_rings_filters_and_lru():
    store = sp.ProfileStore(ring=2, max_procs=4)
    mk = lambda n: {"stacks": {"a;b": n}, "samples": n, "dropped": 0,
                    "window_s": 1.0, "pid": 1, "ts": time.time()}
    # ring: three ingests for one proc keep only the last two windows
    for n in (1, 2, 4):
        store.ingest("w1", mk(n), role="worker", node="nodeA",
                     worker="w1")
    d = store.dump(worker="w1")
    assert len(d["procs"]) == 1
    assert d["procs"][0]["samples"] == 6  # 2 + 4; the 1-window evicted
    assert d["procs"][0]["stacks"] == {"a;b": 6}  # merge-on-read
    # filters: role / node substring match
    store.ingest("node:nodeB", mk(8), role="node", node="nodeB")
    assert len(store.dump()["procs"]) == 2
    assert [p["key"] for p in store.dump(role="node")["procs"]] \
        == ["node:nodeB"]
    assert store.dump(node="nodeA")["procs"][0]["key"] == "w1"
    assert store.dump(worker="zzz")["procs"] == []
    # LRU: a 5th proc evicts the least-recently-ingested (w1)
    store.ingest("w2", mk(1), role="worker")
    store.ingest("w3", mk(1), role="worker")
    store.ingest("w4", mk(1), role="worker")
    keys = {p["key"] for p in store.dump()["procs"]}
    assert len(keys) == 4 and "w1" not in keys
    # top truncation keeps the heaviest stacks
    store.ingest("w9", {"stacks": {"x": 9, "y": 1, "z": 5},
                        "samples": 15, "dropped": 0, "window_s": 1.0,
                        "pid": 2, "ts": time.time()})
    p = store.dump(worker="w9", top=2)["procs"][0]
    assert set(p["stacks"]) == {"x", "z"}


# ------------------------------------------------------------------- e2e

@pytest.fixture(scope="module")
def two_node_profiled():
    import ray_tpu as rt
    rt.init(num_cpus=1, _system_config={
        "object_store_memory_bytes": 64 * MiB,
        "metrics_export_period_s": 0.2,
        "hw_sampler_period_s": 0.5,
    })
    from ray_tpu.core.worker import global_worker
    from ray_tpu.runtime.cluster_backend import start_node
    backend = global_worker.backend
    session = backend.head.call("connect_driver", {})["session"]
    proc = start_node(backend.head_addr, session,
                      resources={"CPU": 1.0, "n2": 1.0})
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            raise RuntimeError(f"second node exited rc={proc.returncode}")
        nodes = backend.head.call("list_nodes")
        if sum(1 for n in nodes if n["alive"]) >= 2:
            break
        time.sleep(0.2)
    else:
        raise RuntimeError("second node never registered")
    yield rt, backend
    proc.terminate()
    try:
        proc.wait(timeout=10)
    finally:
        rt.shutdown()


def _spin_workers(rt_, seconds=1.5):
    """Busy-loop one worker on each node so their profiles have heat."""
    @rt_.remote(num_cpus=1)
    def burn(s):
        t0 = time.monotonic()
        while time.monotonic() - t0 < s:
            sum(i * i for i in range(2000))
        return True

    return [burn.remote(seconds),
            burn.options(resources={"n2": 0.001}).remote(seconds)]


def test_profiles_aggregate_at_head_with_identity(two_node_profiled):
    """Continuous profiles from every role land in the head store tagged
    with node/worker ids; node filters narrow the dump (acceptance:
    head aggregation tags frames with node/worker ids, two nodes)."""
    rt_, backend = two_node_profiled
    head = backend.head
    refs = _spin_workers(rt_)
    assert all(rt_.get(refs, timeout=60))

    by_role, d = {}, {}
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        d = head.call("profiles_dump", {}, timeout=10)
        by_role = {}
        for p in d["procs"]:
            by_role.setdefault(p["role"], []).append(p)
        if {"head", "node", "worker", "driver"} <= set(by_role):
            break
        time.sleep(0.3)
    assert {"head", "node", "worker", "driver"} <= set(by_role), \
        {r: len(v) for r, v in by_role.items()}

    # two node daemons, each tagged with its own node id
    node_ids = {p["node"] for p in by_role["node"]}
    assert len(by_role["node"]) >= 2 and len(node_ids) >= 2, by_role["node"]
    # workers are tagged with BOTH a worker id and the node they ran on
    for p in by_role["worker"]:
        assert p["worker"] and p["node"], p
    # every proc carries real samples and a nonzero aggregated window
    for p in d["procs"]:
        assert p["samples"] > 0 and p["stacks"], p["key"]
    # a node filter narrows to that node's procs only
    some_node = sorted(node_ids)[0]
    narrowed = head.call("profiles_dump", {"node": some_node}, timeout=10)
    assert narrowed["procs"]
    assert all(p["node"] == some_node for p in narrowed["procs"])
    # the head's own profile contains head-process frames (the head runs
    # as `python -m ray_tpu.runtime.head`, so its module folds as
    # __main__; an in-process Head folds as ray_tpu.runtime.head)
    head_stacks = sp.merge_stacks(
        [p["stacks"] for p in by_role["head"]])
    assert any("__main__" in s or "runtime.head" in s
               for s in head_stacks), list(head_stacks)[:3]


def test_profiles_record_burst_fans_out(two_node_profiled):
    """profiles_record bursts head + both node daemons (+ any live
    workers) at a caller-chosen rate and returns fresh captures."""
    rt_, backend = two_node_profiled
    refs = _spin_workers(rt_, seconds=3.0)
    d = backend.head.call(
        "profiles_record", {"seconds": 1.0, "hz": 150.0}, timeout=40)
    assert all(rt_.get(refs, timeout=60))
    roles = {}
    for p in d["procs"]:
        roles.setdefault(p["role"], []).append(p)
    assert "head" in roles and len(roles.get("node", [])) >= 2, \
        {r: len(v) for r, v in roles.items()}
    for p in d["procs"]:
        assert p["samples"] > 0, p["key"]
    # role filter: head only
    d2 = backend.head.call(
        "profiles_record", {"seconds": 0.3, "hz": 99.0, "role": "head"},
        timeout=30)
    assert {p["role"] for p in d2["procs"]} == {"head"}


def test_profile_cli_smoke(two_node_profiled):
    """`ray_tpu profile` renders the top-frames table; --flame emits
    collapsed lines; --speedscope - emits schema-valid JSON."""
    from ray_tpu.scripts import cli

    rt_, backend = two_node_profiled
    address = backend.head_addr
    refs = _spin_workers(rt_, seconds=1.0)
    assert all(rt_.get(refs, timeout=60))
    time.sleep(1.0)  # one more flush so the dump is non-empty

    buf = io.StringIO()
    with redirect_stdout(buf):
        assert cli.main(["profile", "--address", address]) == 0
    out = buf.getvalue()
    assert "process(es)" in out and "[continuous]" in out
    assert "self" in out and "cum" in out and "frame" in out
    assert "node=" in out  # per-proc identity lines

    buf = io.StringIO()
    with redirect_stdout(buf):
        assert cli.main(["profile", "--flame",
                         "--address", address]) == 0
    lines = [ln for ln in buf.getvalue().splitlines() if ln.strip()]
    assert lines
    for ln in lines[:20]:
        stack, _, count = ln.rpartition(" ")
        assert stack and ";" in stack or stack, ln
        assert count.isdigit(), ln

    buf = io.StringIO()
    with redirect_stdout(buf):
        assert cli.main(["profile", "--speedscope", "-",
                         "--address", address]) == 0
    ss = json.loads(buf.getvalue())
    assert ss["$schema"] == \
        "https://www.speedscope.app/file-format-schema.json"
    assert ss["shared"]["frames"] and ss["profiles"]
    prof = ss["profiles"][0]
    assert {"type", "name", "unit", "startValue", "endValue", "samples",
            "weights"} <= set(prof)
    assert prof["endValue"] == sum(prof["weights"]) > 0

    buf = io.StringIO()
    with redirect_stdout(buf):
        assert cli.main(["profile", "--record", "0.5", "--hz", "150",
                         "--head", "--address", address]) == 0
    out = buf.getvalue()
    assert "burst" in out and "process(es)" in out

    buf = io.StringIO()
    with redirect_stdout(buf):
        assert cli.main(["profile", "--format", "json",
                         "--address", address]) == 0
    data = json.loads(buf.getvalue())
    assert data["procs"]
