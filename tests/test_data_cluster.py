"""ray_tpu.data on the multiprocess cluster runtime: block payloads must
flow worker→worker through the C++ shm object store (VERDICT round-1 item 6
done-criterion), and Train ingest must work across real worker processes."""

import numpy as np
import pytest

import ray_tpu as rt
from ray_tpu import data as rd
from ray_tpu.core.worker import global_worker


@pytest.fixture(scope="module")
def cluster_rt():
    rt.init(num_cpus=4, _system_config={
        "object_store_memory_bytes": 256 * 1024 * 1024,
        "worker_pool_prestart": 2,
    })
    yield rt
    rt.shutdown()


def test_blocks_flow_through_shm(cluster_rt):
    n = 200_000  # float64 blocks ≫ the inline threshold → shm-sealed
    ds = rd.from_numpy(np.arange(n, dtype=np.float64), num_blocks=4) \
        .map_batches(lambda a: a * 2.0, batch_format="numpy")
    mat = ds.materialize()
    store = global_worker.backend.object_plane.store
    assert any(store.contains(ref.id().binary()) for ref in mat._refs), \
        "no materialized block found in the shm store"
    out = np.concatenate(
        list(mat.iter_batches(batch_size=50_000, batch_format="numpy")))
    np.testing.assert_allclose(np.sort(out), np.arange(n) * 2.0)


def test_trainer_dataset_over_processes(cluster_rt):
    from ray_tpu import train

    def loop(cfg):
        it = train.get_dataset_shard("train")
        s = sum(int(b["id"].sum()) for b in it.iter_batches(batch_size=16))
        train.report({"sum": s})

    ds = rd.range(64, num_blocks=4)
    trainer = train.JaxTrainer(
        loop, scaling_config=train.ScalingConfig(num_workers=2),
        run_config=train.RunConfig(), datasets={"train": ds})
    result = trainer.fit()
    assert result.metrics["sum"] > 0
