"""util extras: multiprocessing.Pool shim + joblib backend (reference:
python/ray/util/multiprocessing/pool.py, python/ray/util/joblib/)."""

import pytest

import ray_tpu
from ray_tpu.util.multiprocessing import Pool


@pytest.fixture(scope="module")
def rt():
    ray_tpu.init(num_cpus=4)
    yield
    ray_tpu.shutdown()


def _sq(x):
    return x * x


def _add(a, b):
    return a + b


def test_pool_map_and_starmap(rt):
    with Pool(processes=4) as p:
        assert p.map(_sq, range(20)) == [x * x for x in range(20)]
        assert p.starmap(_add, [(1, 2), (3, 4)]) == [3, 7]


def test_pool_apply_and_async(rt):
    with Pool(processes=2) as p:
        assert p.apply(_add, (2, 3)) == 5
        r = p.apply_async(_sq, (9,))
        assert r.get(timeout=30) == 81
        assert r.ready() and r.successful()


def test_pool_imap_ordered_and_unordered(rt):
    with Pool(processes=2) as p:
        assert list(p.imap(_sq, range(10), chunksize=3)) == \
            [x * x for x in range(10)]
        assert sorted(p.imap_unordered(_sq, range(10), chunksize=3)) == \
            sorted(x * x for x in range(10))


def test_pool_initializer_runs(rt):
    import os

    def init_marker(v):
        os.environ["_POOL_MARK"] = str(v)

    def read_marker(_):
        import os as _os
        return _os.environ.get("_POOL_MARK")

    with Pool(processes=2, initializer=init_marker, initargs=(7,)) as p:
        out = p.map(read_marker, range(4), chunksize=1)
    assert all(v == "7" for v in out), out


def test_pool_closed_rejects(rt):
    p = Pool(processes=1)
    p.close()
    with pytest.raises(ValueError):
        p.map(_sq, [1])
    p.join()


def test_joblib_backend(rt):
    joblib = pytest.importorskip("joblib")
    from ray_tpu.util.joblib_backend import register_ray_tpu
    register_ray_tpu()
    from joblib import Parallel, delayed
    with joblib.parallel_backend("ray_tpu", n_jobs=4):
        out = Parallel()(delayed(_sq)(i) for i in range(12))
    assert out == [i * i for i in range(12)]


def test_imap_streams_infinite_input(rt):
    import itertools
    with Pool(processes=2) as p:
        gen = p.imap(_sq, itertools.count())
        first = [next(gen) for _ in range(5)]
    assert first == [0, 1, 4, 9, 16]


def test_join_waits_for_outstanding(rt):
    import os
    import tempfile
    import time as _time
    mark = tempfile.mktemp()

    def slow_write(path):
        _time.sleep(1.0)
        with open(path, "w") as f:
            f.write("done")
        return path

    p = Pool(processes=1)
    p.map_async(slow_write, [mark])
    p.close()
    p.join()
    # the stdlib barrier: after join() the side effect must exist
    assert os.path.exists(mark)
    os.unlink(mark)
