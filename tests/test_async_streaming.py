"""Async actors + streaming generators.

Mirrors the reference's coverage (reference: python/ray/tests/test_asyncio.py
async actor concurrency, test_streaming_generator.py incremental
consumption): an asyncio actor interleaves many in-flight calls on one
process; a streaming task's yields are consumable before the task ends.
"""

import threading
import time

import pytest


# ---------------------------------------------------------------- async actors

def test_async_actor_concurrent_calls(rtpu_cluster):
    ray_tpu = rtpu_cluster

    @ray_tpu.remote
    class AsyncCounter:
        def __init__(self):
            self.peak = 0
            self.inflight = 0

        async def slow(self, t):
            import asyncio
            self.inflight += 1
            self.peak = max(self.peak, self.inflight)
            await asyncio.sleep(t)
            self.inflight -= 1
            return self.peak

        async def peak_seen(self):
            return self.peak

    a = AsyncCounter.remote()
    ray_tpu.get(a.peak_seen.remote(), timeout=30)  # actor cold-start
    t0 = time.monotonic()
    refs = [a.slow.remote(0.3) for _ in range(10)]
    ray_tpu.get(refs, timeout=30)
    elapsed = time.monotonic() - t0
    # serial execution would take >= 3.0s; concurrent interleave ~0.3s
    assert elapsed < 2.0, f"async calls did not interleave ({elapsed:.2f}s)"
    assert ray_tpu.get(a.peak_seen.remote(), timeout=10) >= 2


def test_async_actor_sync_method_and_errors(rtpu_cluster):
    ray_tpu = rtpu_cluster

    @ray_tpu.remote
    class Mixed:
        async def aget(self):
            return 41

        def sget(self):  # sync method on an async actor runs on the loop
            return 1

        async def boom(self):
            raise ValueError("async-boom")

    m = Mixed.remote()
    assert ray_tpu.get(m.aget.remote(), timeout=30) == 41
    assert ray_tpu.get(m.sget.remote(), timeout=10) == 1
    with pytest.raises(Exception, match="async-boom"):
        ray_tpu.get(m.boom.remote(), timeout=10)


def test_async_actor_max_concurrency_limit(rtpu_cluster):
    ray_tpu = rtpu_cluster

    @ray_tpu.remote(max_concurrency=2)
    class Limited:
        def __init__(self):
            self.inflight = 0
            self.peak = 0

        async def slow(self):
            import asyncio
            self.inflight += 1
            self.peak = max(self.peak, self.inflight)
            await asyncio.sleep(0.1)
            self.inflight -= 1
            return self.peak

    a = Limited.remote()
    peaks = ray_tpu.get([a.slow.remote() for _ in range(8)], timeout=30)
    assert max(peaks) <= 2  # semaphore bounds interleave


# ---------------------------------------------------------------- streaming

def test_streaming_task_incremental(rtpu_cluster):
    ray_tpu = rtpu_cluster

    @ray_tpu.remote(num_returns="streaming")
    def gen():
        import time as _t
        t_yield = _t.time()
        for i in range(3):
            yield i, t_yield
        _t.sleep(5)  # long tail AFTER the yields
        yield 99, t_yield

    g = gen.remote()
    first, t_yield = ray_tpu.get(next(g), timeout=30)
    t_recv = time.time()
    assert first == 0
    # incremental contract: the item is consumable well before the task's
    # 5s tail finishes. Measured from the producer's yield (immune to slow
    # worker spawn under suite load on a 1-CPU host).
    assert t_recv - t_yield < 4.0, f"first item took {t_recv - t_yield:.1f}s"
    assert ray_tpu.get(next(g), timeout=5)[0] == 1
    assert ray_tpu.get(next(g), timeout=5)[0] == 2


def test_streaming_task_completion_and_error(rtpu_cluster):
    ray_tpu = rtpu_cluster

    @ray_tpu.remote(num_returns="streaming")
    def ok():
        yield "a"
        yield "b"

    items = [ray_tpu.get(r, timeout=20) for r in ok.remote()]
    assert items == ["a", "b"]

    @ray_tpu.remote(num_returns="streaming")
    def bad():
        yield 1
        raise RuntimeError("stream-boom")

    g = bad.remote()
    assert ray_tpu.get(next(g), timeout=20) == 1
    with pytest.raises(Exception, match="stream-boom"):
        next(g)


def test_streaming_actor_async_generator(rtpu_cluster):
    ray_tpu = rtpu_cluster

    @ray_tpu.remote
    class Tokens:
        async def stream(self, n):
            import asyncio
            for i in range(n):
                await asyncio.sleep(0.01)
                yield f"tok{i}"

    a = Tokens.remote()
    out = [ray_tpu.get(r, timeout=30)
           for r in a.stream.options(num_returns="streaming").remote(4)]
    assert out == ["tok0", "tok1", "tok2", "tok3"]


# ------------------------------------------------------------------- local mode

def test_async_actor_local_mode(rtpu_local):
    ray_tpu = rtpu_local

    @ray_tpu.remote
    class A:
        async def add(self, x):
            return x + 1

    a = A.remote()
    assert ray_tpu.get(a.add.remote(1), timeout=10) == 2


def test_streaming_local_mode(rtpu_local):
    ray_tpu = rtpu_local
    started = threading.Event()

    @ray_tpu.remote(num_returns="streaming")
    def gen():
        yield 1
        yield 2
        started.set()
        time.sleep(3)
        yield 3

    g = gen.remote()
    assert ray_tpu.get(next(g), timeout=10) == 1
    assert ray_tpu.get(next(g), timeout=10) == 2
    # consumed both items while the task is still sleeping
    assert started.wait(5)
    assert ray_tpu.get(next(g), timeout=10) == 3
    with pytest.raises(StopIteration):
        next(g)


def test_abandoned_stream_items_freed(rtpu_cluster):
    """Dropping a generator mid-stream frees the unconsumed items in the
    owner (memory store entries + refcount records) instead of leaking
    them forever."""
    import gc

    ray_tpu = rtpu_cluster
    from ray_tpu.core.worker import global_worker

    @ray_tpu.remote(num_returns="streaming")
    def burst():
        for i in range(50):
            yield ("x" * 2000, i)

    base_tracked = global_worker.refcounter.num_tracked()
    base_entries = global_worker.memory_store.size()
    for _ in range(3):
        g = burst.remote()
        ray_tpu.get(next(g), timeout=60)  # consume ONE of 50
        # wait for completion so all 50 items have arrived
        deadline = time.monotonic() + 30
        while not g.completed() and time.monotonic() < deadline:
            time.sleep(0.05)
        del g
        gc.collect()
    # allow the cleanup path to run
    time.sleep(0.5)
    gc.collect()
    leaked_tracked = global_worker.refcounter.num_tracked() - base_tracked
    leaked_entries = global_worker.memory_store.size() - base_entries
    assert leaked_tracked <= 6, f"refcount entries leaked: {leaked_tracked}"
    assert leaked_entries <= 6, f"memory-store entries leaked: {leaked_entries}"
