"""Object-plane accounting + cluster event journal, end to end.

Acceptance coverage for the observability PR:
  - two-node put/pull/spill workload where `ray_tpu memory` totals match
    each node's ShmStore ground truth EXACTLY (bytes and counts) — the
    directory ships kAlign-aligned arena_bytes so the comparison is
    byte-for-byte, not approximate;
  - kill-a-worker chaos where the head journal carries an ordered
    worker_death -> actor_restarting pair cross-linked by one trace id.

Reference: `ray memory` (python/ray/util/state/memory_utils.py) and
`ray list cluster-events` over the GCS event journal.
"""

import io
import json
import os
import signal
import time
from contextlib import redirect_stdout

import pytest

import ray_tpu as rt

MiB = 1 << 20


@pytest.fixture(scope="module")
def two_node():
    rt.init(num_cpus=1, _system_config={
        "object_store_memory_bytes": 16 * MiB,
        "metrics_export_period_s": 0.2,
    })
    from ray_tpu.core.worker import global_worker
    from ray_tpu.runtime.cluster_backend import start_node
    backend = global_worker.backend
    session = backend.head.call("connect_driver", {})["session"]
    proc = start_node(backend.head_addr, session,
                      resources={"CPU": 1.0, "n2": 1.0})
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            raise RuntimeError(f"second node exited rc={proc.returncode}")
        nodes = backend.head.call("list_nodes")
        if sum(1 for n in nodes if n["alive"]) >= 2:
            break
        time.sleep(0.2)
    else:
        raise RuntimeError("second node never registered")
    yield rt, backend
    proc.terminate()
    try:
        proc.wait(timeout=10)
    finally:
        rt.shutdown()


def test_memory_totals_match_store_ground_truth(two_node):
    """Drive put (primaries on node 1), cross-node get (a secondary on
    node 1) and arena-overflow task returns (primaries + spills on node
    2), then require the head's aggregated directory totals to equal
    each node's ShmStore counters exactly."""
    from ray_tpu.runtime.protocol import RpcClient
    from ray_tpu.scripts import cli

    rt_, backend = two_node
    head = backend.head

    @rt_.remote(resources={"n2": 0.001})
    def make_blob(i):
        return bytes([i % 251]) * MiB

    # 3 driver puts -> primaries in node 1's arena (1 MiB >> the 100KiB
    # inline cutoff, so every object is shm-sealed and directory-tracked)
    keep = [rt_.put(b"p" * MiB) for _ in range(3)]
    # 18 pinned 1 MiB results on node 2's 16 MiB arena -> spill pressure
    results = [make_blob.remote(i) for i in range(18)]
    done, _ = rt_.wait(results, num_returns=len(results), timeout=180)
    assert len(done) == len(results)
    # pull one result across nodes -> a secondary copy in node 1's arena
    first = rt_.get(results[0], timeout=120)
    assert len(first) == MiB

    nodes = [n for n in head.call("list_nodes") if n["alive"]]
    assert len(nodes) == 2
    probes = {n["node_id"]: RpcClient(n["address"], name="acct-probe")
              for n in nodes}
    # expected directory population once every owner has flushed:
    # 3 puts + 1 pulled secondary (node 1) + 18 task results (node 2,
    # spilled ones included — they stay tracked, just not arena-resident)
    expect_rows = 3 + 1 + len(results)

    od, last = {}, None
    deadline = time.monotonic() + 90
    try:
        while time.monotonic() < deadline:
            od = head.call("objects_dump", timeout=10)
            totals = od.get("totals", {})
            tracked = sum(t.get("count", 0) for node_t in totals.values()
                          for t in node_t.values())
            ok, last = tracked == expect_rows, [("tracked", tracked)]
            for nid, c in probes.items():
                st = c.call("store_stats", timeout=10)
                t = totals.get(nid, {})
                arena = sum(t.get(r, {}).get("arena_bytes", 0)
                            for r in ("primary", "secondary"))
                count = sum(t.get(r, {}).get("count", 0)
                            for r in ("primary", "secondary"))
                last.append((nid[:8], arena, st["bytes_used"],
                             count, st["num_objects"]))
                ok &= arena == st["bytes_used"] \
                    and count == st["num_objects"]
            if ok:
                break
            time.sleep(0.3)
        else:
            raise AssertionError(
                f"accounting never matched store ground truth: {last}")

        # the CLI sees the same aggregation (acceptance: `ray_tpu
        # memory` totals are the thing that must match, not just the RPC)
        buf = io.StringIO()
        with redirect_stdout(buf):
            assert cli.main(["memory", "--format", "json",
                             "--address", backend.head_addr]) == 0
        via_cli = json.loads(buf.getvalue())
        for nid, c in probes.items():
            st = c.call("store_stats", timeout=10)
            t = via_cli["totals"].get(nid, {})
            assert sum(t.get(r, {}).get("arena_bytes", 0)
                       for r in ("primary", "secondary")) \
                == st["bytes_used"], (nid, t, st)
            assert sum(t.get(r, {}).get("count", 0)
                       for r in ("primary", "secondary")) \
                == st["num_objects"], (nid, t, st)

        roles = {r["role"] for r in od["rows"]}
        assert {"primary", "secondary", "spilled"} <= roles, roles
        spilled = sum(t.get("spilled", {}).get("count", 0)
                      for t in od["totals"].values())
        assert spilled >= 1, "16 MiB arena under 18 MiB pinned: must spill"

        # the overflow made it into the journal (worker-originated,
        # sequenced at head arrival), and seqs are strictly ordered
        evs = head.call("events_dump", timeout=10)
        spill_evs = [e for e in evs if e["type"] == "spill_overflow"]
        assert spill_evs and all(e["bytes"] > 0 for e in spill_evs)
        assert len([e for e in evs if e["type"] == "node_register"]) >= 2
        seqs = [e["seq"] for e in evs]
        assert seqs == sorted(seqs) and len(set(seqs)) == len(seqs)
    finally:
        for c in probes.values():
            c.close()
        del keep, results, first


def test_worker_death_journal_ordering(two_node):
    """SIGKILL an actor's worker: the journal must record worker_death
    (with the exit cause) BEFORE the actor_restarting it triggers, both
    stamped with the same trace id."""
    rt_, backend = two_node
    head = backend.head

    @rt_.remote(max_restarts=1)
    class Phoenix:
        def pid(self):
            return os.getpid()

    a = Phoenix.remote()
    pid1 = rt_.get(a.pid.remote(), timeout=60)
    os.kill(pid1, signal.SIGKILL)

    wd = ar = None
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        evs = head.call("events_dump", timeout=10)
        wds = [e for e in evs if e["type"] == "worker_death"]
        ars = [e for e in evs if e["type"] == "actor_restarting"]
        if wds and ars:
            wd, ar = wds[-1], ars[-1]
            break
        time.sleep(0.2)
    assert wd and ar, "journal never saw the death -> restart pair"
    assert "exit code" in wd["exit_cause"] or "oom" in wd["exit_cause"]
    assert wd["trace_id"] and wd["trace_id"] == ar["trace_id"], \
        "death and restart must share one trace id"
    assert wd["seq"] < ar["seq"], "causal order: death before restart"

    # the restarted incarnation serves again from a new process
    pid2 = None
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        try:
            pid2 = rt_.get(a.pid.remote(), timeout=15)
            break
        except rt_.exceptions.ActorError:
            time.sleep(0.2)
    assert pid2 is not None and pid2 != pid1
