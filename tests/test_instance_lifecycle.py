"""Crash-consistent instance lifecycle tests (reference scope:
autoscaler v2 instance_manager + instance_storage semantics).

Covers the PR-11 tentpole done-criteria: every launch drives a
persisted, journaled REQUESTED→ALLOCATED→RUNNING→DRAINING→TERMINATED
record; SIGKILLing the autoscaler mid-launch and restarting it converges
to zero orphans, asserted against the provider's live-handle ledger AND
the journaled transition history; a double restart journals no duplicate
transitions.
"""

import json
import os
import signal
import subprocess
import sys
import time

import pytest

from ray_tpu.runtime import instance_manager as im


# ----------------------------------------------------------- unit: machine


class _Journal:
    """Capture journal emissions as (event_type, fields) tuples."""

    def __init__(self):
        self.events = []

    def __call__(self, etype, **fields):
        self.events.append((etype, fields))

    def types(self, node_id=None):
        return [t for t, f in self.events
                if node_id is None or f.get("node_id") == node_id]


def test_happy_path_transitions_persist_and_journal():
    store = im.MemoryInstanceStore()
    j = _Journal()
    mgr = im.InstanceManager(store, journal=j)

    rec = mgr.request("cpu", {"CPU": 2.0}, "n1")
    assert rec.state == im.REQUESTED
    assert rec.trace_id, "request() must mint a trace id"
    assert store.load_all()["n1"]["state"] == im.REQUESTED

    mgr.transition("n1", im.ALLOCATED, metadata={"pid": 123})
    assert store.load_all()["n1"]["metadata"] == {"pid": 123}
    mgr.transition("n1", im.RUNNING)
    assert mgr.live_counts() == {"cpu": 1}
    mgr.transition("n1", im.DRAINING)
    # DRAINING holds no capacity: a drain must not block a scale-up
    assert mgr.live_counts() == {}
    mgr.transition("n1", im.TERMINATED)

    # terminal states delete the persisted key; the journal IS the history
    assert store.load_all() == {}
    assert j.types("n1") == ["instance_requested", "instance_allocated",
                             "instance_running", "instance_draining",
                             "instance_terminated"]
    # one trace id per instance, stamped on every transition
    traces = {f["trace_id"] for _, f in j.events}
    assert traces == {rec.trace_id}
    assert [s for s, _ in rec.history] == [
        im.REQUESTED, im.ALLOCATED, im.RUNNING, im.DRAINING, im.TERMINATED]


def test_invalid_transitions_rejected():
    mgr = im.InstanceManager(im.MemoryInstanceStore())
    mgr.request("cpu", {"CPU": 1.0}, "n1")
    with pytest.raises(im.InvalidTransition):
        mgr.transition("n1", im.DRAINING)   # REQUESTED cannot drain
    with pytest.raises(im.InvalidTransition):
        mgr.transition("n1", im.DEAD)       # never ran, cannot be DEAD
    mgr.transition("n1", im.LAUNCH_FAILED)
    with pytest.raises(im.InvalidTransition):
        mgr.transition("n1", im.RUNNING)    # terminal states are final
    with pytest.raises(KeyError):
        mgr.transition("ghost", im.RUNNING)


def test_reconcile_adopt_orphan_dead_drained_unrecorded():
    """All five reconcile verdicts, against a store 'restored' from a
    previous incarnation."""
    store = im.MemoryInstanceStore()
    seeder = im.InstanceManager(store)
    seeder.request("cpu", {"CPU": 1.0}, "adopt-me")       # will register
    seeder.request("cpu", {"CPU": 1.0}, "orphan-me")      # never registers
    r = seeder.request("cpu", {"CPU": 1.0}, "was-running")
    seeder.transition(r.node_id, im.ALLOCATED)
    seeder.transition(r.node_id, im.RUNNING)
    d = seeder.request("cpu", {"CPU": 1.0}, "was-draining")
    seeder.transition(d.node_id, im.ALLOCATED)
    seeder.transition(d.node_id, im.RUNNING)
    seeder.transition(d.node_id, im.DRAINING)

    j = _Journal()
    mgr = im.InstanceManager(store, journal=j)
    assert mgr.load() == 4
    killed = []
    actions = mgr.reconcile(
        registered={"adopt-me"},
        provider_live={"ghost-id": {"pid": 999999}},
        terminate=lambda rec: killed.append(rec.node_id),
        orphan_grace_s=0.0)

    assert actions["adopted"] == ["adopt-me"]
    assert actions["orphaned"] == ["orphan-me"]
    assert actions["dead"] == ["was-running"]
    assert actions["drained"] == ["was-draining"]
    assert actions["unrecorded"] == ["ghost-id"]
    assert sorted(killed) == ["ghost-id", "orphan-me"]
    assert mgr.get("adopt-me").state == im.RUNNING
    assert mgr.get("orphan-me").state == im.TERMINATED
    assert mgr.get("was-running").state == im.DEAD
    assert mgr.get("was-draining").state == im.TERMINATED
    assert "instance_unrecorded" in [t for t, _ in j.events]
    # only the adopted record still persists (it is live)
    assert set(store.load_all()) == {"adopt-me"}


def test_reconcile_grace_leaves_young_launches_pending():
    store = im.MemoryInstanceStore()
    seeder = im.InstanceManager(store)
    seeder.request("cpu", {"CPU": 1.0}, "young")
    mgr = im.InstanceManager(store)
    mgr.load()
    actions = mgr.reconcile(registered=set(), orphan_grace_s=60.0)
    assert actions["pending"] == ["young"]
    assert mgr.get("young").state == im.REQUESTED


def test_reconcile_idempotent_no_duplicate_journal():
    """A second reconcile over converged state journals nothing — a
    double autoscaler restart must not duplicate transition history."""
    store = im.MemoryInstanceStore()
    seeder = im.InstanceManager(store)
    seeder.request("cpu", {"CPU": 1.0}, "n1")
    j = _Journal()
    mgr = im.InstanceManager(store, journal=j)
    mgr.load()
    mgr.reconcile(registered={"n1"}, orphan_grace_s=0.0)
    n_events = len(j.events)
    assert j.types("n1") == ["instance_running"]
    again = mgr.reconcile(registered={"n1"}, orphan_grace_s=0.0)
    assert len(j.events) == n_events, "idempotent reconcile re-journaled"
    assert all(not v for v in again.values())

    # ...and a second load() must not clobber the in-memory RUNNING state
    # with the stale persisted copy
    mgr.load()
    assert mgr.get("n1").state == im.RUNNING


def test_instance_manager_imports_without_jax():
    """CI-hygiene satellite: the autoscaler daemon imports this module;
    it must never pull in the accelerator stack (same contract as
    llm/request_log.py)."""
    code = ("import sys\n"
            "import ray_tpu.runtime.instance_manager\n"
            "import ray_tpu.util.fault_injector\n"
            "import ray_tpu.autoscaler\n"
            "print('jax' in sys.modules)\n")
    out = subprocess.run([sys.executable, "-c", code],
                         capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr
    assert out.stdout.strip() == "False", out.stdout


# ----------------------------------------------- integration: full journal


def _wait(predicate, timeout, period=0.2, desc="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        val = predicate()
        if val:
            return val
        time.sleep(period)
    raise AssertionError(f"timed out waiting for {desc}")


def _boot_head(session):
    from ray_tpu.runtime.cluster_backend import start_head
    from ray_tpu.runtime.protocol import RpcClient, RpcError
    head_proc, address = start_head(session)
    probe = RpcClient(address, name="lifecycle-test")

    def up():
        try:
            probe.call("list_nodes", timeout=5)
            return True
        except RpcError:
            return False
    _wait(up, 30, desc="head boot")
    return head_proc, address, probe


def _instance_events(probe, node_id):
    evs = probe.call("events_dump", {}, timeout=10)
    return [e for e in evs if e.get("node_id") == node_id
            and (e["type"].startswith("instance_")
                 or e["type"] == "node_launch_failed")]


def test_full_lifecycle_journal_chain():
    """One launch end to end: `events` replays the whole
    REQUESTED→ALLOCATED→RUNNING→DRAINING→TERMINATED chain in order, every
    event carrying the instance's single trace id, and the scale-up /
    scale-down decisions join on that same trace."""
    from ray_tpu.autoscaler import (Autoscaler, LocalNodeProvider,
                                    NodeTypeSpec)

    session = os.urandom(4).hex()
    head_proc, address, probe = _boot_head(session)
    scaler = Autoscaler(
        address, LocalNodeProvider(address, session),
        node_types={"w": NodeTypeSpec({"CPU": 1.0}, max_workers=1,
                                      min_workers=1)},
        idle_timeout_s=1.0, poll_period_s=0.2).start()
    try:
        # min_workers floor launches with no demand; wait for RUNNING
        rec = _wait(
            lambda: next((r for r in scaler.im.records(im.RUNNING)), None),
            45, desc="node to reach RUNNING")
        nid = rec.node_id
        # the persisted record rides the head's KV table while live
        assert probe.call("kv_get", {"key": im.KV_PREFIX + nid},
                          timeout=5)["state"] == im.RUNNING

        # drop the floor -> idle drain -> DRAINING -> TERMINATED
        scaler.node_types["w"].min_workers = 0
        _wait(lambda: scaler.im.get(nid).state == im.TERMINATED, 30,
              desc="idle drain to TERMINATED")

        chain = _instance_events(probe, nid)
        assert [e["type"] for e in chain] == [
            "instance_requested", "instance_allocated", "instance_running",
            "instance_draining", "instance_terminated"], chain
        traces = {e["trace_id"] for e in chain}
        assert len(traces) == 1 and rec.trace_id in traces
        # scaling decisions join the same trace
        decisions = [e for e in probe.call("events_dump", {}, timeout=10)
                     if e["type"].startswith("autoscaler_scale")
                     and e.get("node_id") == nid]
        assert {e["type"] for e in decisions} == {"autoscaler_scale_up",
                                                 "autoscaler_scale_down"}
        assert all(e["trace_id"] == rec.trace_id for e in decisions)
        # terminal record left no KV residue and no live provider handle
        assert probe.call("kv_keys", {"prefix": im.KV_PREFIX},
                          timeout=5) == []
        assert scaler.provider.list_live() == {}
    finally:
        scaler.stop()
        probe.close()
        head_proc.terminate()
        try:
            head_proc.wait(timeout=5)
        except Exception:
            head_proc.kill()


# ----------------------------------------------------- chaos: crash launch


def _spawn_runner(address, opts, fault=""):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    if fault:
        env["RTPU_FAULT_INJECT"] = fault
    else:
        env.pop("RTPU_FAULT_INJECT", None)
    proc = subprocess.Popen(
        [sys.executable, "-m", "ray_tpu.autoscaler", address,
         json.dumps(opts)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True)
    return proc


def _kill_ledger_pids(ledger_path):
    try:
        with open(ledger_path, encoding="utf-8") as f:
            for line in f:
                try:
                    entry = json.loads(line)
                except ValueError:
                    continue
                if entry.get("op") == "create":
                    try:
                        os.kill(int(entry["pid"]), signal.SIGKILL)
                    except (ProcessLookupError, PermissionError):
                        pass
    except FileNotFoundError:
        pass


@pytest.mark.chaos
def test_sigkill_mid_launch_restart_converges_no_orphans(tmp_path):
    """The tentpole crash-consistency criterion: SIGKILL the autoscaler
    BETWEEN create_node and the ALLOCATED persist; restart it; the
    write-ahead REQUESTED record re-adopts the node that registered while
    the autoscaler was down. Zero orphans asserted against the provider's
    live-handle ledger AND the journaled transition history; a second
    kill+restart journals no duplicate transitions."""
    from ray_tpu.autoscaler import LocalNodeProvider

    session = os.urandom(4).hex()
    ledger = str(tmp_path / "provider.ledger")
    opts = {"session": session, "ledger_path": ledger,
            "poll_period_s": 0.2,
            "node_types": {"w": {"resources": {"CPU": 1.0},
                                 "max_workers": 1, "min_workers": 1}}}
    head_proc, address, probe = _boot_head(session)
    runner = None
    try:
        # --- crash: dies by SIGKILL right after the provider create
        runner = _spawn_runner(address, opts,
                               fault="autoscaler.post_create=kill9")
        assert runner.wait(timeout=60) == -signal.SIGKILL
        keys = _wait(lambda: probe.call(
            "kv_keys", {"prefix": im.KV_PREFIX}, timeout=5), 10,
            desc="write-ahead record")
        assert len(keys) == 1
        record = probe.call("kv_get", {"key": keys[0]}, timeout=5)
        nid = record["node_id"]
        # died before ALLOCATED could persist — that is the crash window
        assert record["state"] == im.REQUESTED
        # ...but the provider ledger already owns the subprocess
        provider = LocalNodeProvider(address, session, ledger_path=ledger)
        assert set(provider.list_live()) == {nid}
        # the launched daemon registers with the head on its own
        _wait(lambda: any(n["node_id"] == nid and n["alive"]
                          for n in probe.call("list_nodes", timeout=5)),
              45, desc="orphan node registration")

        # --- restart: reconcile must adopt, not orphan-kill or relaunch
        runner = _spawn_runner(address, opts)
        _wait(lambda: probe.call(
            "kv_get", {"key": im.KV_PREFIX + nid},
            timeout=5)["state"] == im.RUNNING, 45,
            desc="adoption to RUNNING")
        types = [e["type"] for e in _instance_events(probe, nid)]
        assert types == ["instance_requested", "instance_running"], types
        traces = {e["trace_id"] for e in _instance_events(probe, nid)}
        assert len(traces) == 1
        # zero orphans: provider owns exactly the adopted node, nothing
        # was terminated, nothing unrecorded, no second launch
        assert set(provider.list_live()) == {nid}
        assert probe.call("kv_keys", {"prefix": im.KV_PREFIX},
                          timeout=5) == [im.KV_PREFIX + nid]
        evs = probe.call("events_dump", {}, timeout=10)
        assert not [e for e in evs if e["type"] in
                    ("instance_terminated", "instance_unrecorded",
                     "node_launch_failed")], evs

        # --- double restart: idempotency, no duplicate journal entries
        runner.send_signal(signal.SIGKILL)
        runner.wait(timeout=10)
        runner = _spawn_runner(address, opts)
        time.sleep(3.0)  # several reconcile passes
        assert runner.poll() is None, runner.stdout.read()
        types = [e["type"] for e in _instance_events(probe, nid)]
        assert types == ["instance_requested", "instance_running"], \
            f"double restart duplicated transitions: {types}"
        assert set(provider.list_live()) == {nid}
    finally:
        if runner is not None:
            runner.kill()
        _kill_ledger_pids(ledger)
        probe.close()
        head_proc.terminate()
        try:
            head_proc.wait(timeout=5)
        except Exception:
            head_proc.kill()


@pytest.mark.chaos
def test_requested_orphan_terminated_after_restart(tmp_path):
    """Crash BEFORE create_node: the write-ahead REQUESTED record exists
    but no machine does. The restarted autoscaler must terminate the
    orphan record past the grace window and journal it — no handle leak,
    no zombie KV entry."""
    session = os.urandom(4).hex()
    ledger = str(tmp_path / "provider.ledger")
    base = {"session": session, "ledger_path": ledger,
            "poll_period_s": 0.2,
            "config": {"instance_orphan_grace_s": 0.5}}
    opts1 = {**base, "node_types": {"w": {"resources": {"CPU": 1.0},
                                          "max_workers": 1,
                                          "min_workers": 1}}}
    # the restarted incarnation keeps min_workers=0 so the orphan kill is
    # the ONLY lifecycle activity to assert on
    opts2 = {**base, "node_types": {"w": {"resources": {"CPU": 1.0},
                                          "max_workers": 1,
                                          "min_workers": 0}}}
    head_proc, address, probe = _boot_head(session)
    runner = None
    try:
        runner = _spawn_runner(address, opts1,
                               fault="autoscaler.pre_create=kill9")
        assert runner.wait(timeout=60) == -signal.SIGKILL
        keys = _wait(lambda: probe.call(
            "kv_keys", {"prefix": im.KV_PREFIX}, timeout=5), 10,
            desc="write-ahead record")
        nid = probe.call("kv_get", {"key": keys[0]}, timeout=5)["node_id"]
        time.sleep(1.0)  # age the record past the 0.5s orphan grace

        runner = _spawn_runner(address, opts2)
        _wait(lambda: probe.call("kv_keys", {"prefix": im.KV_PREFIX},
                                 timeout=5) == [], 30,
              desc="orphan record cleanup")
        chain = _instance_events(probe, nid)
        assert [e["type"] for e in chain] == [
            "instance_requested", "instance_terminated"], chain
        assert chain[-1].get("detail") == "orphaned-launch"
        # nothing was ever created: the ledger owns no live pid
        from ray_tpu.autoscaler import LocalNodeProvider
        assert LocalNodeProvider(address, session,
                                 ledger_path=ledger).list_live() == {}
    finally:
        if runner is not None:
            runner.kill()
        _kill_ledger_pids(ledger)
        probe.close()
        head_proc.terminate()
        try:
            head_proc.wait(timeout=5)
        except Exception:
            head_proc.kill()


@pytest.mark.chaos
def test_stillborn_node_journaled_as_launch_failed(fault_injector):
    """Satellite: a launched daemon that dies before registering becomes
    LAUNCH_FAILED, journaled as ``node_launch_failed`` with node_type and
    exit info — visible in `events`, not a silent log line."""
    from ray_tpu.autoscaler import (Autoscaler, LocalNodeProvider,
                                    NodeTypeSpec)

    session = os.urandom(4).hex()
    head_proc, address, probe = _boot_head(session)
    # armed via env so only the autoscaler-spawned daemons (which inherit
    # it) die at boot; the already-running head is unaffected
    os.environ[fault_injector.ENV_VAR] = "node.boot=exit:3"
    scaler = Autoscaler(
        address, LocalNodeProvider(address, session),
        node_types={"w": NodeTypeSpec({"CPU": 1.0}, max_workers=1,
                                      min_workers=1)},
        idle_timeout_s=5.0, poll_period_s=0.2).start()
    try:
        failed = _wait(
            lambda: [e for e in probe.call("events_dump",
                                           {"type": "node_launch_failed"},
                                           timeout=5)
                     if e.get("detail") == "died-pre-register"],
            45, desc="node_launch_failed journal entry")
        ev = failed[0]
        assert ev["node_type"] == "w"
        assert ev["exit_info"] == "3"
        assert ev["trace_id"]
        rec = scaler.im.get(ev["node_id"])
        assert rec is not None and rec.state == im.LAUNCH_FAILED
    finally:
        os.environ.pop(fault_injector.ENV_VAR, None)
        scaler.stop()
        probe.close()
        head_proc.terminate()
        try:
            head_proc.wait(timeout=5)
        except Exception:
            head_proc.kill()
