"""Data: zip, pandas interop, write APIs, torch iterator (reference:
data/dataset.py zip/write_*/to_pandas, data/iterator.py
iter_torch_batches)."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu import data as rd


@pytest.fixture(scope="module")
def rt():
    ray_tpu.init(num_cpus=4)
    yield
    ray_tpu.shutdown()


def test_zip_dict_blocks(rt):
    a = rd.from_numpy({"x": np.arange(10)}, num_blocks=3)
    b = rd.from_numpy({"y": np.arange(10) * 2}, num_blocks=2)
    z = a.zip(b)
    rows = z.take_all()
    assert len(rows) == 10
    assert all(r["y"] == 2 * r["x"] for r in rows)
    assert z.num_blocks() == 3  # left side's block count carries over


def test_zip_column_collision_suffixes(rt):
    a = rd.from_numpy({"x": np.arange(4)})
    b = rd.from_numpy({"x": np.arange(4) + 100})
    rows = a.zip(b).take_all()
    assert rows[0].keys() == {"x", "x_1"}
    assert rows[2]["x"] == 2 and rows[2]["x_1"] == 102


def test_zip_row_blocks_pairs(rt):
    a = rd.from_items(["a", "b", "c"])
    b = rd.from_items([1, 2, 3])
    assert a.zip(b).take_all() == [("a", 1), ("b", 2), ("c", 3)]


def test_zip_length_mismatch_raises(rt):
    with pytest.raises(ValueError):
        rd.from_items([1, 2]).zip(rd.from_items([1, 2, 3]))


def test_zip_applies_pending_transforms(rt):
    a = rd.range(6).map(lambda r: {"x": r["id"] * 10})
    b = rd.range(6).filter(lambda r: True)
    rows = a.zip(b).take_all()
    assert rows[3]["x"] == 30 and rows[3]["id"] == 3


def test_pandas_roundtrip(rt):
    import pandas as pd
    df = pd.DataFrame({"a": [1, 2, 3], "b": [4.0, 5.0, 6.0]})
    ds = rd.from_pandas(df, num_blocks=2)
    assert ds.count() == 3
    back = ds.to_pandas()
    assert list(back.columns) == ["a", "b"]
    assert back["a"].tolist() == [1, 2, 3]


def test_write_json_roundtrip(rt, tmp_path):
    ds = rd.from_numpy({"v": np.arange(7)}, num_blocks=2)
    paths = ds.write_json(str(tmp_path / "out"))
    assert len(paths) == 2 and all(p.endswith(".jsonl") for p in paths)
    back = rd.read_json([str(tmp_path / "out")])
    vals = sorted(r["v"] for r in back.take_all())
    assert vals == list(np.arange(7))


def test_write_csv_roundtrip(rt, tmp_path):
    ds = rd.from_numpy({"a": np.arange(5), "b": np.arange(5) * 1.5})
    paths = ds.write_csv(str(tmp_path / "csvs"))
    back = rd.read_csv([str(tmp_path / "csvs")])
    rows = sorted(back.take_all(), key=lambda r: r["a"])
    assert len(rows) == 5
    assert float(rows[4]["b"]) == 6.0


def test_write_parquet_roundtrip(rt, tmp_path):
    pytest.importorskip("pyarrow")
    ds = rd.from_numpy({"k": np.arange(6)}, num_blocks=2)
    paths = ds.write_parquet(str(tmp_path / "pq"))
    assert len(paths) == 2
    back = rd.read_parquet([str(tmp_path / "pq")])
    assert sorted(r["k"] for r in back.take_all()) == list(np.arange(6))


def test_iter_torch_batches(rt):
    torch = pytest.importorskip("torch")
    ds = rd.from_numpy({"x": np.arange(10, dtype=np.float32)})
    batches = list(ds.iterator().iter_torch_batches(batch_size=4))
    assert [len(b["x"]) for b in batches] == [4, 4, 2]
    assert isinstance(batches[0]["x"], torch.Tensor)
    assert batches[0]["x"].dtype == torch.float32
    total = torch.cat([b["x"] for b in batches]).sum().item()
    assert total == float(np.arange(10).sum())


def test_write_respects_limit(rt, tmp_path):
    # limit() truncates the boundary block in write paths too
    rd.range(100, num_blocks=1).limit(5).write_json(str(tmp_path / "lim"))
    back = rd.read_json([str(tmp_path / "lim")])
    assert back.count() == 5


def test_write_npy_tensor_roundtrip(rt, tmp_path):
    arr = np.arange(12, dtype=np.float32).reshape(6, 2)
    rd.from_numpy(arr, num_blocks=2).write_npy(str(tmp_path / "npy"))
    back = rd.read_npy([str(tmp_path / "npy")])
    got = np.concatenate([b for b in back.iter_batches(
        batch_size=6, batch_format="numpy")])
    assert got.shape == (6, 2)


def test_write_npy_rejects_tables(rt, tmp_path):
    import pytest as _pt
    with _pt.raises(Exception):  # TypeError surfaces through the task
        rd.from_items([{"a": 1}, {"a": 2}]).write_npy(str(tmp_path / "bad"))


def test_zip_double_collision_keeps_all(rt):
    a = rd.from_numpy({"x": np.arange(3), "x_1": np.arange(3) + 10})
    b = rd.from_numpy({"x": np.arange(3) + 100})
    rows = a.zip(b).take_all()
    assert rows[0].keys() == {"x", "x_1", "x_2"}
    assert rows[1]["x_1"] == 11 and rows[1]["x_2"] == 101
