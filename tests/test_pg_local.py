"""Placement-group API in local mode (separate module: needs a fresh,
non-cluster ray_tpu.init)."""

import ray_tpu as rt
from ray_tpu.util import placement_group


def test_local_mode_pg():
    rt.init(local_mode=True, num_cpus=4)
    try:
        pg = placement_group([{"CPU": 2}], strategy="PACK")
        assert pg.wait(5)
        bad = placement_group([{"CPU": 64}], strategy="PACK")
        assert not bad.wait(0.5)
        assert bad.state()["state"] == "INFEASIBLE"
    finally:
        rt.shutdown()
