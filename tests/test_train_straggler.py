"""Cross-host straggler attribution, end to end.

One host of a 2-worker CPU train is slowed via the env-armed fault
point ``train.report.rank1=sleep:...`` (the workers inherit the spec
from the driver's environment). Every rank publishes its per-phase
step times to the head KV; host 0 compares them each report and must
surface the lag as:

  - ``train_phase_skew_s{phase,host}`` gauges (seconds behind the
    fastest host), and
  - ONE ``train_straggler`` journal event naming the lagging host,
    trace-id-linked to the run (``train:<run-key>``),

asserted from the head's journal + metrics dump — the operator path,
not internals. The gang runs WITHOUT jax collectives on purpose:
per-step collectives equalize wall step times across ranks (the fast
host absorbs the skew inside its collective wait), so uncoupled ranks
are the shape where latest-window comparison must do the work.
"""

import os
import time

import pytest

import ray_tpu as rt
from ray_tpu import train

SLEEP_S = 0.4
FAST_S = 0.05


@pytest.fixture(scope="module")
def straggler_rt():
    from ray_tpu.util import fault_injector as fi
    # armed BEFORE init: node daemon + workers inherit the spec, and
    # fire() lazily reloads the env inside each worker process
    os.environ[fi.ENV_VAR] = f"train.report.rank1=sleep:{SLEEP_S}"
    rt.init(num_cpus=2, _system_config={
        "object_store_memory_bytes": 64 * 1024 * 1024,
        "metrics_export_period_s": 0.2,
    })
    yield rt
    rt.shutdown()
    os.environ.pop(fi.ENV_VAR, None)
    fi.reset()


def _make_loop():
    def loop(cfg):
        import time as _t

        ctx = train.get_context()
        t0 = _t.monotonic()
        # wall-clock bounded (not step-count) so the slowed rank ends
        # near the fast one despite ~9x slower steps
        while _t.monotonic() - t0 < cfg["run_s"]:
            _t.sleep(cfg["fast_s"])
            # rank 1's report() entry hits the armed sleep fault, so its
            # implicit 'step' phase runs ~(fast_s + sleep_s)
            train.report({"ok": 1})
    return loop


def test_one_slow_host_surfaces_as_straggler(straggler_rt, tmp_path):
    from ray_tpu.core.worker import global_worker

    result = train.JaxTrainer(
        _make_loop(),
        train_loop_config={"run_s": 4.0, "fast_s": FAST_S},
        scaling_config=train.ScalingConfig(num_workers=2),
        run_config=train.RunConfig(
            name="straggle", storage_path=str(tmp_path))).fit()
    assert result.error is None, result.error
    # sanity: rank 0 got many fast steps in, so it ran the comparison
    # many times while rank 1's slowed windows were live in the KV
    assert result.metrics["_step"] > 20, result.metrics

    head = global_worker.backend.head

    # --- the journal names the lagging host, trace-linked to the run
    evs = []
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        evs = head.call("events_dump", {"type": "train_straggler"},
                        timeout=10)
        if evs:
            break
        time.sleep(0.3)
    assert evs, "train_straggler never reached the head journal"
    ev = evs[-1]
    assert ev["host"] == "1" and ev["rank"] == 1, ev
    assert ev["world_size"] == 2
    assert ev["trace_id"].startswith("train:"), ev
    factors = ev["slowdown_factors"]
    assert "step" in factors and factors["step"] > 2.0, factors
    # only ONE event per excursion: a persistent straggler must not
    # journal once per report (rank 0 reported dozens of times)
    assert len(evs) <= 2, [e["seq"] for e in evs]

    # --- the skew gauge attributes seconds-behind-fastest to host 1
    skew, agg = {}, {}
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline:
        agg = head.call("metrics_dump", timeout=10) or {}
        skew = (agg.get("train_phase_skew_s") or {}).get("values", {})
        if any(k.endswith("|1") for k in skew):
            break
        time.sleep(0.3)
    assert skew, f"no train_phase_skew_s series in {sorted(agg)}"
    host1 = {k: v for k, v in skew.items() if k.endswith("|1")}
    assert host1, skew
    # host 1 lags by roughly the injected sleep (lenient: scheduling
    # noise, but it must be well clear of zero and of host 0's skew)
    assert max(host1.values()) > SLEEP_S / 2, host1
    host0 = {k: v for k, v in skew.items() if k.endswith("|0")}
    if host0:
        assert max(host0.values()) <= max(host1.values()), (host0, host1)
