"""Metrics + task-timeline tests (reference scope: util/metrics API,
TaskEventBuffer→GcsTaskManager timeline, `ray timeline` export)."""

import time

import pytest

import ray_tpu as rt
from ray_tpu.core.worker import global_worker
from ray_tpu.util import metrics as metrics_mod
from ray_tpu.util.metrics import Counter, Gauge, Histogram, aggregate


@pytest.fixture(scope="module")
def cluster_rt():
    metrics_mod.clear_registry()
    rt.init(num_cpus=2, _system_config={
        "object_store_memory_bytes": 64 * 1024 * 1024,
        "metrics_export_period_s": 0.2,
    })
    yield rt
    rt.shutdown()
    metrics_mod.clear_registry()


def test_metric_types_and_snapshot():
    metrics_mod.clear_registry()
    c = Counter("req_total", tag_keys=("route",))
    c.inc(1, tags={"route": "/a"})
    c.inc(2, tags={"route": "/a"})
    g = Gauge("queue_depth")
    g.set(7)
    h = Histogram("latency_s", boundaries=(0.1, 1.0))
    h.observe(0.05)
    h.observe(0.5)
    h.observe(5.0)
    snap = metrics_mod.snapshot()
    assert snap["req_total"]["values"][("/a",)] == 3
    assert snap["queue_depth"]["values"][()] == 7
    assert snap["latency_s"]["values"][()]["counts"] == [1, 1, 1]
    assert snap["latency_s"]["values"][()]["n"] == 3
    with pytest.raises(ValueError):
        c.inc(-1)
    metrics_mod.clear_registry()


def test_aggregate_across_workers():
    w1 = {"c": {"type": "counter", "desc": "", "tag_keys": (),
                "values": {(): 2.0}}}
    w2 = {"c": {"type": "counter", "desc": "", "tag_keys": (),
                "values": {(): 3.0}},
          "g": {"type": "gauge", "desc": "", "tag_keys": (),
                "values": {(): 9.0}}}
    agg = aggregate({"w1": w1, "w2": w2})
    assert agg["c"]["values"][()] == 5.0
    assert agg["g"]["values"][()] == 9.0


def test_worker_metrics_flow_to_head(cluster_rt):
    @rt.remote
    def work(i):
        from ray_tpu.util.metrics import Counter
        Counter("tasks_done_test").inc()
        return i

    assert sorted(rt.get([work.remote(i) for i in range(4)],
                         timeout=60)) == [0, 1, 2, 3]
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline:
        agg = global_worker.backend.head.call("metrics_dump")
        got = agg.get("tasks_done_test", {}).get("values", {})
        if sum(got.values()) >= 4:
            return
        time.sleep(0.3)
    pytest.fail(f"metrics never aggregated at head: {agg}")


def test_task_timeline_records_spans(cluster_rt):
    @rt.remote
    def slow():
        time.sleep(0.05)
        return 1

    rt.get([slow.remote() for _ in range(3)], timeout=60)
    deadline = time.monotonic() + 15
    events = []
    while time.monotonic() < deadline:
        events = global_worker.backend.head.call("timeline_dump")
        if sum(1 for e in events if e["name"].endswith("slow")) >= 3:
            break
        time.sleep(0.3)
    spans = [e for e in events if e["name"].endswith("slow")]
    assert len(spans) >= 3, events
    assert all(e["end"] >= e["start"] + 0.04 for e in spans)
    from ray_tpu.runtime.events import to_chrome_trace
    trace = to_chrome_trace(spans)
    assert all(t["ph"] == "X" and t["dur"] > 0 for t in trace)


def test_state_api_lists_tasks_and_objects(cluster_rt):
    """`list tasks` / `list objects` (reference: util/state/api.py:1011
    list_tasks, list_objects) — task spans from the head's event buffer,
    object summaries from owner telemetry."""
    import time as _t

    from ray_tpu.util import state as state_api

    @rt.remote
    def traced(x):
        return x + 1

    ref = traced.remote(1)
    keep = rt.put(list(range(2000)))  # a tracked object  # noqa: F841
    assert rt.get(ref, timeout=60) == 2
    # telemetry flushes every metrics_export_period_s; poll until visible
    deadline = _t.monotonic() + 30
    tasks, objects = [], []
    while _t.monotonic() < deadline:
        tasks = state_api.list_tasks()
        objects = state_api.list_objects()
        if any("traced" in (t.get("name") or "") for t in tasks) \
                and objects:
            break
        _t.sleep(0.5)
    names = [t.get("name") for t in tasks]
    assert any("traced" in n for n in names), names
    span = next(t for t in tasks if "traced" in (t.get("name") or ""))
    assert span.get("ok") is True and "worker" in span
    assert any(o.get("tracked", 0) > 0 for o in objects), objects
