"""Metrics + task-timeline tests (reference scope: util/metrics API,
TaskEventBuffer→GcsTaskManager timeline, `ray timeline` export)."""

import time

import pytest

import ray_tpu as rt
from ray_tpu.core.worker import global_worker
from ray_tpu.util import metrics as metrics_mod
from ray_tpu.util.metrics import Counter, Gauge, Histogram, aggregate


@pytest.fixture(scope="module")
def cluster_rt():
    metrics_mod.clear_registry()
    rt.init(num_cpus=2, _system_config={
        "object_store_memory_bytes": 64 * 1024 * 1024,
        "metrics_export_period_s": 0.2,
    })
    yield rt
    rt.shutdown()
    metrics_mod.clear_registry()


def test_metric_types_and_snapshot():
    metrics_mod.clear_registry()
    c = Counter("req_total", tag_keys=("route",))
    c.inc(1, tags={"route": "/a"})
    c.inc(2, tags={"route": "/a"})
    g = Gauge("queue_depth")
    g.set(7)
    h = Histogram("latency_s", boundaries=(0.1, 1.0))
    h.observe(0.05)
    h.observe(0.5)
    h.observe(5.0)
    snap = metrics_mod.snapshot()
    assert snap["req_total"]["values"][("/a",)] == 3
    assert snap["queue_depth"]["values"][()] == 7
    assert snap["latency_s"]["values"][()]["counts"] == [1, 1, 1]
    assert snap["latency_s"]["values"][()]["n"] == 3
    with pytest.raises(ValueError):
        c.inc(-1)
    metrics_mod.clear_registry()


def test_aggregate_across_workers():
    w1 = {"c": {"type": "counter", "desc": "", "tag_keys": (),
                "values": {(): 2.0}}}
    w2 = {"c": {"type": "counter", "desc": "", "tag_keys": (),
                "values": {(): 3.0}},
          "g": {"type": "gauge", "desc": "", "tag_keys": (),
                "values": {(): 9.0}}}
    agg = aggregate({"w1": w1, "w2": w2})
    assert agg["c"]["values"][()] == 5.0
    assert agg["g"]["values"][()] == 9.0


def test_worker_metrics_flow_to_head(cluster_rt):
    @rt.remote
    def work(i):
        from ray_tpu.util.metrics import Counter
        Counter("tasks_done_test").inc()
        return i

    assert sorted(rt.get([work.remote(i) for i in range(4)],
                         timeout=60)) == [0, 1, 2, 3]
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline:
        agg = global_worker.backend.head.call("metrics_dump")
        got = agg.get("tasks_done_test", {}).get("values", {})
        if sum(got.values()) >= 4:
            return
        time.sleep(0.3)
    pytest.fail(f"metrics never aggregated at head: {agg}")


def test_task_timeline_records_spans(cluster_rt):
    @rt.remote
    def slow():
        time.sleep(0.05)
        return 1

    rt.get([slow.remote() for _ in range(3)], timeout=60)
    deadline = time.monotonic() + 15
    events = []
    while time.monotonic() < deadline:
        events = global_worker.backend.head.call("timeline_dump")
        if sum(1 for e in events if e["name"].endswith("slow")) >= 3:
            break
        time.sleep(0.3)
    spans = [e for e in events if e["name"].endswith("slow")]
    assert len(spans) >= 3, events
    assert all(e["end"] >= e["start"] + 0.04 for e in spans)
    from ray_tpu.runtime.events import to_chrome_trace
    trace = to_chrome_trace(spans)
    assert all(t["ph"] == "X" and t["dur"] > 0 for t in trace)


def test_nested_submit_single_trace(cluster_rt):
    """Cross-process tracing: driver → outer task → nested inner task
    (two worker processes) must export ONE trace whose spans link via
    parent_span_id — the wire-propagated context, not name matching."""
    @rt.remote
    def trc_inner():
        return 1

    @rt.remote
    def trc_outer():
        return rt.get(trc_inner.remote()) + 1

    assert rt.get(trc_outer.remote(), timeout=60) == 2
    deadline = time.monotonic() + 20
    events, outer, inner = [], None, None
    while time.monotonic() < deadline:
        events = global_worker.backend.head.call("timeline_dump")
        outer = next((e for e in events if "trc_outer" in e["name"]
                      and e.get("kind") == "task"), None)
        inner = next((e for e in events if "trc_inner" in e["name"]
                      and e.get("kind") == "task"), None)
        if outer is not None and inner is not None:
            break
        time.sleep(0.3)
    assert outer is not None and inner is not None, events
    # one trace: the nested submit inherited the outer task's ambient
    # context, across a separate worker process
    assert outer.get("trace_id")
    assert inner.get("trace_id") == outer["trace_id"]
    assert inner["parent_span_id"] == outer["span_id"]
    assert not outer.get("parent_span_id")  # driver-rooted
    mine = [e for e in events if e.get("trace_id") == outer["trace_id"]]
    assert sum(1 for e in mine if e.get("parent_span_id")) >= 3

    # OTLP export carries the linkage verbatim
    from ray_tpu.util import tracing
    doc = tracing.events_to_otlp(mine)
    spans = doc["resourceSpans"][0]["scopeSpans"][0]["spans"]
    assert {s["traceId"] for s in spans} == {outer["trace_id"]}
    assert sum(1 for s in spans if s.get("parentSpanId")) >= 3

    # head-side assembly: one root (the outer exec span), inner beneath it
    roots = tracing.assemble_trace(events, trace_id=outer["trace_id"])
    assert len(roots) == 1, roots
    assert "trc_outer" in roots[0]["name"]

    def names(span):
        yield span["name"]
        for c in span["children"]:
            yield from names(c)
    assert any("trc_inner" in n for n in names(roots[0]))
    # selection by task_id resolves to the same trace
    by_task = tracing.assemble_trace(events, task_id=inner["task_id"])
    assert by_task and by_task[0]["trace_id"] == outer["trace_id"]


def test_scheduler_phase_spans_and_queue_metrics(cluster_rt):
    """Queueing delay is separable from execution: every exec span gets a
    ::sched companion (submit→start, child of the exec span), the head
    stamps lease:: phase events, and submit_to_start/queue_depth
    aggregate in metrics_dump."""
    @rt.remote
    def phased():
        time.sleep(0.02)
        return 1

    assert rt.get(phased.remote(), timeout=60) == 1
    deadline = time.monotonic() + 20
    events, ex, sched = [], None, None
    while time.monotonic() < deadline:
        events = global_worker.backend.head.call("timeline_dump")
        ex = next((e for e in events if "phased" in e["name"]
                   and e.get("kind") == "task"), None)
        sched = next((e for e in events if "phased" in e["name"]
                      and e.get("kind") == "sched"), None)
        if ex is not None and sched is not None:
            break
        time.sleep(0.3)
    assert ex is not None and sched is not None, events
    # the sched span ends where execution begins: queue time vs run time
    assert sched["end"] <= ex["start"] + 1e-6
    assert sched["start"] <= sched["end"]
    assert sched["trace_id"] == ex["trace_id"]
    assert sched["parent_span_id"] == ex["span_id"]
    # head-side scheduler-phase events (lease grant path)
    assert any(e.get("kind") == "sched" and e["name"].startswith("lease::")
               and e.get("worker") == "head" for e in events), \
        [e["name"] for e in events if e.get("kind") == "sched"]
    # aggregate view at the head
    agg = {}
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline:
        agg = global_worker.backend.head.call("metrics_dump")
        h = agg.get("submit_to_start")
        if h and sum(v["n"] for v in h["values"].values()) >= 1:
            break
        time.sleep(0.3)
    assert agg.get("submit_to_start", {}).get("type") == "histogram", \
        sorted(agg)
    assert "queue_depth" in agg


def test_old_format_wire_frames_accepted():
    """Mixed-version compat: a submit payload from a peer that predates
    trace propagation (no trace/span/submit_ts fields) still parses, and
    its events still export with the deterministic fabricated ids."""
    from ray_tpu.core.ids import TaskID
    from ray_tpu.core.task_spec import TaskSpec
    from ray_tpu.runtime import wire
    from ray_tpu.util import tracing

    spec = TaskSpec(task_id=TaskID.from_random(), name="legacy",
                    function_key=b"fn:x", resources={"CPU": 1.0})
    payload, _ = wire.task_to_wire(spec, function_key="fn:x")
    # new stamps present on the modern frame...
    assert len(payload["trace_id"]) == 32
    assert len(payload["span_id"]) == 16
    # ...and absent on an old peer's frame — which must still be accepted
    for k in ("trace_id", "span_id", "parent_span_id", "submit_ts",
              "lease_ts"):
        payload.pop(k, None)
    back = wire.task_from_wire(payload)
    assert back.name == "legacy"
    assert back.task_id == spec.task_id
    # OTLP export of a traceless event fabricates deterministic ids
    e = {"name": "legacy", "task_id": "ab" * 8, "kind": "task",
         "start": 1.0, "end": 2.0, "ok": True}
    doc = tracing.events_to_otlp([e])
    span = doc["resourceSpans"][0]["scopeSpans"][0]["spans"][0]
    assert len(span["traceId"]) == 32 and len(span["spanId"]) == 16
    assert "parentSpanId" not in span


def test_state_api_lists_tasks_and_objects(cluster_rt):
    """`list tasks` / `list objects` (reference: util/state/api.py:1011
    list_tasks, list_objects) — task spans from the head's event buffer,
    object summaries from owner telemetry."""
    import time as _t

    from ray_tpu.util import state as state_api

    @rt.remote
    def traced(x):
        return x + 1

    ref = traced.remote(1)
    keep = rt.put(list(range(2000)))  # a tracked object  # noqa: F841
    assert rt.get(ref, timeout=60) == 2
    # telemetry flushes every metrics_export_period_s; poll until visible
    deadline = _t.monotonic() + 30
    tasks, objects = [], []
    while _t.monotonic() < deadline:
        tasks = state_api.list_tasks()
        objects = state_api.list_objects()
        if any("traced" in (t.get("name") or "") for t in tasks) \
                and objects:
            break
        _t.sleep(0.5)
    names = [t.get("name") for t in tasks]
    assert any("traced" in n for n in names), names
    span = next(t for t in tasks if "traced" in (t.get("name") or ""))
    assert span.get("ok") is True and "worker" in span
    assert any(o.get("tracked", 0) > 0 for o in objects), objects
