"""Transport-layer tests, parametrized over both implementations.

The native C++ epoll transport (protocol_native / src/transport.cc) and the
pure-Python fallback speak the same wire format and expose the same API;
every behavior here must hold for both (reference test role:
src/ray/rpc/test/grpc_server_client_test.cc).
"""

import threading
import time

import pytest

from ray_tpu.runtime import protocol, protocol_native


IMPLS = [
    pytest.param((protocol.PyRpcServer, protocol.PyRpcClient), id="python"),
    pytest.param((protocol_native.RpcServer, protocol_native.RpcClient),
                 id="native"),
]


def _echo_handlers():
    def echo(payload, ctx):
        return payload

    def boom(payload, ctx):
        raise ValueError("boom")

    def deferred(payload, ctx):
        def later():
            time.sleep(0.05)
            ctx.reply({"deferred": payload})
        threading.Thread(target=later, daemon=True).start()
        return protocol.DEFERRED

    return {"echo": echo, "boom": boom, "deferred": deferred,
            "ping": lambda p, c: "pong"}


@pytest.fixture(params=IMPLS)
def impl(request):
    server_cls, client_cls = request.param
    server = server_cls(_echo_handlers(), name="t")
    client = client_cls(server.address, name="t-client")
    yield server, client
    client.close()
    server.stop()


def test_unary_roundtrip(impl):
    server, client = impl
    assert client.call("echo", {"x": 1}) == {"x": 1}
    assert client.call("ping") == "pong"


def test_application_error_propagates(impl):
    server, client = impl
    with pytest.raises(ValueError, match="boom"):
        client.call("boom")


def test_unknown_method(impl):
    server, client = impl
    with pytest.raises(protocol.RpcError, match="no handler"):
        client.call("nope")


def test_deferred_reply(impl):
    server, client = impl
    assert client.call("deferred", 7) == {"deferred": 7}


def test_pipelined_async_calls(impl):
    server, client = impl
    futs = [client.call_async("echo", i) for i in range(500)]
    assert [f.result(timeout=10) for f in futs] == list(range(500))


def test_batch_call_cb(impl):
    server, client = impl
    results = {}
    done = threading.Event()

    def cb(i, value, error):
        results[i] = (value, error)
        if len(results) == 100:
            done.set()

    client.call_batch_cb("echo", [{"i": i} for i in range(100)], cb)
    assert done.wait(timeout=10)
    for i in range(100):
        value, error = results[i]
        assert error is None and value == {"i": i}


def test_large_frame(impl):
    server, client = impl
    blob = b"z" * (8 * 1024 * 1024)  # > one read() buffer
    assert client.call("echo", blob, timeout=30) == blob


def test_oneway_does_not_crash(impl):
    server, client = impl
    got = []
    server.handlers["note"] = lambda p, c: got.append(p)
    client.oneway("note", 42)
    deadline = time.monotonic() + 5
    while not got and time.monotonic() < deadline:
        time.sleep(0.01)
    assert got == [42]


def test_connect_refused_raises(impl):
    _, client_cls = type(impl[0]), type(impl[1])
    dead = client_cls("127.0.0.1:1", name="dead")
    with pytest.raises(protocol.RpcError):
        dead.call("ping", timeout=3.0)
    dead.close()


def test_server_stop_fails_pending(impl):
    server, client = impl

    def hang(payload, ctx):
        return protocol.DEFERRED  # never replies

    server.handlers["hang"] = hang
    fut = client.call_async("hang")
    time.sleep(0.1)
    server.stop()
    with pytest.raises(protocol.RpcError):
        fut.result(timeout=10)


def test_on_disconnect_fires(impl):
    server, client = impl
    seen = threading.Event()
    server.on_disconnect = lambda peer: seen.set()
    client.call("ping")  # establish
    client.close()
    assert seen.wait(timeout=5)


def test_peer_identity_stable(impl):
    server, client = impl
    peers = []
    server.handlers["who"] = lambda p, ctx: peers.append(ctx.peer) or "ok"
    client.call("who")
    client.call("who")
    assert len(peers) == 2 and peers[0] == peers[1]


def test_inline_methods_preserve_order(impl):
    server, client = impl
    seen = []

    def ordered(payload, ctx):
        seen.append(payload)
        return None

    server.handlers["ordered"] = ordered
    server.inline_methods.add("ordered")
    futs = [client.call_async("ordered", i) for i in range(200)]
    for f in futs:
        f.result(timeout=10)
    assert seen == list(range(200))


def test_chaos_injection(impl, monkeypatch):
    server, client = impl
    from ray_tpu.core import config as config_mod
    monkeypatch.setattr(config_mod.GlobalConfig, "testing_rpc_failure",
                        "flaky=2")
    protocol.reset_chaos()
    server.handlers["flaky"] = lambda p, c: "ok"
    failures = 0
    for _ in range(4):
        try:
            client.call("flaky", timeout=5)
        except protocol.RpcError:
            failures += 1
    assert failures == 2
    protocol.reset_chaos()


def test_cross_impl_wire_compat():
    """Python client <-> native server and vice versa (same wire format)."""
    nserver = protocol_native.RpcServer(_echo_handlers(), name="x")
    pclient = protocol.PyRpcClient(nserver.address, name="x-py")
    assert pclient.call("echo", [1, 2]) == [1, 2]
    pclient.close()
    nserver.stop()

    pserver = protocol.PyRpcServer(_echo_handlers(), name="y")
    nclient = protocol_native.RpcClient(pserver.address, name="y-nat")
    assert nclient.call("echo", {"k": "v"}) == {"k": "v"}
    nclient.close()
    pserver.stop()


def test_kv_fastpath_roundtrip():
    """Fast frames are served inside the C loop; host accessors see the
    same table (native head KV, transport.cc FastKV)."""
    server = protocol_native.RpcServer({}, name="fkv")
    assert server.enable_kv_fastpath(incarnation=42)
    client = protocol_native.RpcClient(server.address, name="fkv-c")
    try:
        # ping carries the incarnation
        status, val = client.call_fast(protocol_native.FAST_PING, timeout=10)
        assert status == 1
        import struct as _s
        assert _s.unpack("<Q", val)[0] == 42
        # put (created) / get / overwrite semantics / del
        st, _ = client.call_fast(protocol_native.FAST_PUT, b"k1", b"v1",
                                 flags=1, timeout=10)
        assert st == 1  # created
        st, v = client.call_fast(protocol_native.FAST_GET, b"k1", timeout=10)
        assert (st, v) == (1, b"v1")
        st, _ = client.call_fast(protocol_native.FAST_PUT, b"k1", b"v2",
                                 flags=0, timeout=10)  # no-overwrite
        assert st == 0  # existed, not replaced
        st, v = client.call_fast(protocol_native.FAST_GET, b"k1", timeout=10)
        assert v == b"v1"
        # host-side view is the same table
        assert server.kv_fast_get(b"k1") == b"v1"
        server.kv_fast_put(b"k2", b"host")
        st, v = client.call_fast(protocol_native.FAST_GET, b"k2", timeout=10)
        assert (st, v) == (1, b"host")
        assert set(server.kv_fast_items()) == {b"k1", b"k2"}
        v0 = server.kv_fast_version()
        st, _ = client.call_fast(protocol_native.FAST_DEL, b"k1", timeout=10)
        assert st == 1
        assert server.kv_fast_version() > v0
        st, _ = client.call_fast(protocol_native.FAST_GET, b"k1", timeout=10)
        assert st == 0
    finally:
        client.close()
        server.stop()


def test_kv_fastpath_mixed_with_pickle_calls():
    """Fast and regular frames interleave on one connection."""
    server = protocol_native.RpcServer(_echo_handlers(), name="mix")
    server.enable_kv_fastpath()
    client = protocol_native.RpcClient(server.address, name="mix-c")
    try:
        for i in range(50):
            client.call_fast(protocol_native.FAST_PUT, b"k%d" % i,
                             b"v%d" % i, flags=1, timeout=10)
            assert client.call("echo", i, timeout=10) == i
        st, v = client.call_fast(protocol_native.FAST_GET, b"k7", timeout=10)
        assert (st, v) == (1, b"v7")
    finally:
        client.close()
        server.stop()
