"""Step-time attribution smoke tests (ISSUE 7 CI guard): the phase
breakdown must sum to the measured step time, ride the train gauges, and
land in the task event buffer as a train_step span tree — so the
profiler itself can't silently rot."""

import functools

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ray_tpu.models import llama
from ray_tpu.train import PHASES, StepBreakdown, profile_train_step


@pytest.fixture(scope="module")
def setup():
    import optax
    cfg = llama.LlamaConfig.tiny(n_layers=2, dtype=jnp.float32)
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    opt = optax.sgd(1e-2)
    opt_state = opt.init(params)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0,
                                cfg.vocab_size)
    loss = functools.partial(llama.loss_fn, cfg=cfg)
    return loss, opt, params, opt_state, tokens


class TestStepProfiler:
    def test_breakdown_sums_to_step_time(self, setup):
        loss, opt, params, opt_state, tokens = setup
        bd = profile_train_step(loss, opt, params, opt_state, tokens,
                                steps=2, warmup=1, emit=False)
        assert isinstance(bd, StepBreakdown)
        assert set(bd.phases) == set(PHASES)
        assert all(v >= 0.0 for v in bd.phases.values())
        assert bd.step_time_s > 0.0
        assert bd.compile_time_s >= 0.0
        # the invariant the attribution maintains by construction
        assert sum(bd.phases.values()) == pytest.approx(
            bd.step_time_s, rel=1e-6)
        # phase_ms mirrors phases in milliseconds
        assert bd.phase_ms()["forward"] == pytest.approx(
            bd.phases["forward"] * 1e3)

    def test_profile_does_not_touch_training_state(self, setup):
        loss, opt, params, opt_state, tokens = setup
        before = float(loss(params, tokens))
        profile_train_step(loss, opt, params, opt_state, tokens,
                           steps=1, warmup=0, emit=False)
        assert float(loss(params, tokens)) == pytest.approx(before)

    def test_gauges_emitted(self, setup):
        from ray_tpu.util import metrics
        loss, opt, params, opt_state, tokens = setup
        metrics.clear_registry()
        try:
            profile_train_step(loss, opt, params, opt_state, tokens,
                               steps=1, warmup=0, emit=True)
            snap = metrics.snapshot()
            assert "train_phase_time_s" in snap
            tagged = snap["train_phase_time_s"]["values"]
            assert {k[0] for k in tagged} == set(PHASES)
            assert "train_step_time_s" in snap
        finally:
            metrics.clear_registry()

    def test_spans_recorded_and_cli_selectable(self, setup, monkeypatch):
        from ray_tpu.core import worker as worker_mod
        from ray_tpu.runtime.events import TaskEventBuffer
        from ray_tpu.util.tracing import latest_train_step

        loss, opt, params, opt_state, tokens = setup
        buf = TaskEventBuffer()

        class FakeBackend:
            event_buffer = buf

        monkeypatch.setattr(worker_mod.global_worker, "backend",
                            FakeBackend(), raising=False)
        bd = profile_train_step(loss, opt, params, opt_state, tokens,
                                steps=1, warmup=0, emit=True)
        events = buf.drain()
        steps = [e for e in events if e["kind"] == "train_step"]
        assert len(steps) == 1
        phases = [e for e in events if e["kind"] == "train_phase"]
        assert {e["name"] for e in phases} == set(PHASES)
        assert all(e["parent_span_id"] == steps[0]["span_id"]
                   for e in phases)
        # children partition the parent window (abs tolerance: the span
        # window lives on unix-epoch floats, which can't hold rel=1e-6
        # of a millisecond step)
        assert steps[0]["end"] - steps[0]["start"] == pytest.approx(
            bd.step_time_s, abs=1e-3)
        # the CLI's --train-step selector finds the tree
        tree = latest_train_step(events)
        assert tree is not None and tree["name"] == "train_step"
        assert {c["name"] for c in tree["children"]} == set(PHASES)

    def test_report_phases_rides_session_gauges(self, tmp_path):
        from ray_tpu.train.session import TrainContext
        from ray_tpu.util import metrics
        metrics.clear_registry()
        try:
            ctx = TrainContext(rank=0, world_size=1,
                               storage_path=str(tmp_path))
            ctx.report({"loss": 1.0})  # first report only arms the clock
            ctx.report({"loss": 0.9,
                        "phases": {"forward": 0.25, "backward": 0.5}})
            tagged = metrics.snapshot()["train_phase_time_s"]["values"]
            assert tagged[("forward",)] == pytest.approx(0.25)
            assert tagged[("backward",)] == pytest.approx(0.5)
        finally:
            metrics.clear_registry()


@pytest.mark.slow
class TestRematPolicyTiming:
    def test_selective_backward_not_slower_than_full(self):
        """The lever's direction on CPU: selective remat (saves matmul
        outputs) must not lose to full remat (recomputes the whole layer
        in backward). Generous margin — this guards the sign, not the
        magnitude."""
        import time
        cfg_full = llama.LlamaConfig.tiny(
            dim=128, n_layers=4, ffn_dim=512, dtype=jnp.float32,
            remat_policy="full")
        cfg_sel = llama.LlamaConfig.tiny(
            dim=128, n_layers=4, ffn_dim=512, dtype=jnp.float32,
            remat_policy="selective")
        params = llama.init_params(cfg_full, jax.random.PRNGKey(0))
        tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 128), 0,
                                    cfg_full.vocab_size)

        def timed(cfg):
            fn = jax.jit(jax.value_and_grad(
                functools.partial(llama.loss_fn, cfg=cfg)))
            jax.block_until_ready(fn(params, tokens))
            times = []
            for _ in range(5):
                t0 = time.perf_counter()
                jax.block_until_ready(fn(params, tokens))
                times.append(time.perf_counter() - t0)
            return sorted(times)[len(times) // 2]

        t_full, t_sel = timed(cfg_full), timed(cfg_sel)
        assert t_sel <= t_full * 1.1, (t_sel, t_full)
