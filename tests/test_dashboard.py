"""Dashboard HTTP surface (reference scope: dashboard head REST +
state aggregation)."""

import json
import urllib.request

import pytest

import ray_tpu as rt
from ray_tpu.core.worker import global_worker
from ray_tpu.dashboard import Dashboard


@pytest.fixture(scope="module")
def cluster_rt():
    rt.init(num_cpus=2, _system_config={
        "object_store_memory_bytes": 64 * 1024 * 1024,
        "metrics_export_period_s": 0.2,
    })
    yield rt
    rt.shutdown()


def test_dashboard_endpoints(cluster_rt):
    @rt.remote
    class Pinger:
        def ping(self):
            return "pong"

    p = Pinger.remote()
    assert rt.get(p.ping.remote(), timeout=60) == "pong"

    dash = Dashboard(global_worker.backend.head_addr)
    base = f"http://127.0.0.1:{dash.port}"
    try:
        with urllib.request.urlopen(f"{base}/api/state", timeout=30) as r:
            state = json.loads(r.read())
        assert state["nodes"] and any(a["class"] == "Pinger"
                                      for a in state["actors"])
        with urllib.request.urlopen(f"{base}/api/metrics", timeout=30) as r:
            json.loads(r.read())
        with urllib.request.urlopen(f"{base}/api/timeline",
                                    timeout=30) as r:
            json.loads(r.read())
        with urllib.request.urlopen(f"{base}/", timeout=30) as r:
            assert b"ray_tpu" in r.read()
        with urllib.request.urlopen(f"{base}/api/jobs", timeout=30) as r:
            assert json.loads(r.read()) == []
    finally:
        dash.stop()
