"""Dashboard HTTP surface (reference scope: dashboard head REST +
state aggregation)."""

import json
import urllib.request

import pytest

import ray_tpu as rt
from ray_tpu.core.worker import global_worker
from ray_tpu.dashboard import Dashboard


@pytest.fixture(scope="module")
def cluster_rt():
    rt.init(num_cpus=2, _system_config={
        "object_store_memory_bytes": 64 * 1024 * 1024,
        "metrics_export_period_s": 0.2,
    })
    yield rt
    rt.shutdown()


def test_dashboard_endpoints(cluster_rt):
    @rt.remote
    class Pinger:
        def ping(self):
            return "pong"

    p = Pinger.remote()
    assert rt.get(p.ping.remote(), timeout=60) == "pong"

    dash = Dashboard(global_worker.backend.head_addr)
    base = f"http://127.0.0.1:{dash.port}"
    try:
        with urllib.request.urlopen(f"{base}/api/state", timeout=30) as r:
            state = json.loads(r.read())
        assert state["nodes"] and any(a["class"] == "Pinger"
                                      for a in state["actors"])
        with urllib.request.urlopen(f"{base}/api/metrics", timeout=30) as r:
            json.loads(r.read())
        with urllib.request.urlopen(f"{base}/api/timeline",
                                    timeout=30) as r:
            json.loads(r.read())
        with urllib.request.urlopen(f"{base}/", timeout=30) as r:
            assert b"ray_tpu" in r.read()
        with urllib.request.urlopen(f"{base}/api/jobs", timeout=30) as r:
            assert json.loads(r.read()) == []
    finally:
        dash.stop()


def test_node_stats_and_profile(cluster_rt):
    """Per-node agent stats + on-demand worker stack dump (reference:
    dashboard agent reporter + py-spy profile_manager roles)."""
    import time

    @rt.remote
    class Sleeper:
        def busy_wait(self, s):
            time.sleep(s)
            return "done"

    a = Sleeper.remote()
    assert rt.get(a.busy_wait.remote(0.0), timeout=60) == "done"  # ready
    ref = a.busy_wait.remote(8.0)   # a clearly-identifiable stack to find

    dash = Dashboard(global_worker.backend.head_addr)
    base = f"http://127.0.0.1:{dash.port}"
    try:
        rows = json.loads(urllib.request.urlopen(
            f"{base}/api/nodes", timeout=30).read())
        live = [r for r in rows if r["alive"]]
        assert live, rows
        st = live[0]["stats"]
        assert st["cpus"] >= 1 and st["mem_total"] > 0
        assert "store" in st and "capacity" in st["store"]
        workers = [w for w in st["workers"] if w["rss"]]
        assert workers, st["workers"]

        # profile actor workers: ONE of them (the Sleeper, not any
        # earlier test's actor) must show busy_wait on a stack
        actor_workers = [w for w in st["workers"]
                         if w["state"] == "actor"]
        assert actor_workers, st["workers"]
        found = False
        for w in actor_workers:
            prof = json.loads(urllib.request.urlopen(
                f"{base}/api/profile?node_id={live[0]['node_id']}"
                f"&worker_id={w['worker_id']}", timeout=30).read())
            assert prof["num_threads"] >= 1
            if "busy_wait" in "\n".join(prof["stacks"].values()):
                found = True
        assert found, "no actor worker stack showed busy_wait"
    finally:
        dash.stop()
    # OUTSIDE finally: a drain failure must not mask the real assertion
    assert rt.get(ref, timeout=60) == "done"
