"""ray_tpu.serve tests on the multiprocess cluster runtime.

Coverage model mirrors the reference's serve tests (reference:
python/ray/serve/tests/test_standalone.py, test_deploy.py,
test_autoscaling_policy.py scope): deploy/call/delete, multi-replica
routing, composition handles, HTTP ingress round-trip, replica failure
recovery, and queue-length autoscaling.
"""

import json
import os
import time
import urllib.request

import pytest

import ray_tpu as rt
from ray_tpu import serve


@pytest.fixture(scope="module")
def serve_rt():
    rt.init(num_cpus=4, _system_config={
        "object_store_memory_bytes": 128 * 1024 * 1024,
        "worker_pool_prestart": 2,
    })
    yield rt
    serve.shutdown()
    rt.shutdown()


def test_function_deployment_roundtrip(serve_rt):
    @serve.deployment
    def square(x):
        return x * x

    handle = serve.run(square.bind())
    assert handle.remote(7).result(timeout=60) == 49
    assert "square" in serve.status()


def test_class_deployment_and_methods(serve_rt):
    @serve.deployment(num_replicas=1)
    class Greeter:
        def __init__(self, prefix):
            self.prefix = prefix

        def __call__(self, name):
            return f"{self.prefix} {name}"

        def shout(self, name):
            return f"{self.prefix} {name}!!"

    h = serve.run(Greeter.bind("hello"))
    assert h.remote("world").result(timeout=60) == "hello world"
    assert h.shout.remote("tpu").result(timeout=30) == "hello tpu!!"


def test_request_latency_outcome_tags(serve_rt):
    """Timed-out requests must OBSERVE into the latency histogram with
    outcome="timeout" (previously they never observed, so p99 silently
    excluded the worst requests); completed ones land outcome="ok"."""
    from ray_tpu.exceptions import GetTimeoutError
    from ray_tpu.util import metrics as metrics_mod

    @serve.deployment(name="lagger", num_replicas=1)
    def lagger(delay_s):
        time.sleep(delay_s)
        return delay_s

    h = serve.run(lagger.bind())
    assert h.remote(0.0).result(timeout=60) == 0.0
    with pytest.raises(GetTimeoutError):
        h.remote(8.0).result(timeout=0.5)

    def outcomes():
        # keys are (deployment, outcome, attempt); sum over attempt
        fam = metrics_mod.snapshot().get("serve_request_latency_s", {})
        out = {}
        for key, hist in fam.get("values", {}).items():
            if key[0] == "lagger":
                out[key[:2]] = out.get(key[:2], 0) + hist["n"]
        return out

    # the timeout observes synchronously at result() time; the ok path
    # observes from the reaper thread when the reply lands
    assert outcomes().get(("lagger", "timeout"), 0) >= 1, outcomes()
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        if outcomes().get(("lagger", "ok"), 0) >= 1:
            break
        time.sleep(0.2)
    assert outcomes().get(("lagger", "ok"), 0) >= 1, outcomes()
    serve.delete("lagger")


def test_multi_replica_routing(serve_rt):
    @serve.deployment(num_replicas=2, max_ongoing_requests=4)
    class PidSvc:
        def __call__(self, _):
            return os.getpid()

    h = serve.run(PidSvc.bind())
    pids = {h.remote(i).result(timeout=60) for i in range(20)}
    assert len(pids) >= 2, f"pow-2 routing never spread load: {pids}"
    assert os.getpid() not in pids


def test_composition_child_handle(serve_rt):
    @serve.deployment
    class Doubler:
        def __call__(self, x):
            return 2 * x

    @serve.deployment
    class Ingress:
        def __init__(self, child):
            self.child = child

        def __call__(self, x):
            return self.child.remote(x).result(timeout=30) + 1

    h = serve.run(Ingress.bind(Doubler.bind()))
    assert h.remote(10).result(timeout=60) == 21


def test_http_proxy_roundtrip(serve_rt):
    @serve.deployment(name="adder")
    class Adder:
        def __call__(self, body):
            return {"sum": body["a"] + body["b"]}

    serve.run(Adder.bind())
    port = serve.start_http_proxy()
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/adder",
        data=json.dumps({"a": 2, "b": 40}).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=60) as resp:
        payload = json.loads(resp.read())
    assert payload["result"]["sum"] == 42
    # unknown route -> 404
    try:
        urllib.request.urlopen(
            f"http://127.0.0.1:{port}/nosuch", timeout=30)
        raised = False
    except urllib.error.HTTPError as e:
        raised = e.code == 404
    assert raised


def test_replica_failure_recovery(serve_rt):
    @serve.deployment(name="fragile", num_replicas=1)
    class Fragile:
        def __call__(self, _):
            return os.getpid()

    h = serve.run(Fragile.bind())
    pid = h.remote(0).result(timeout=60)
    os.kill(pid, 9)
    deadline = time.monotonic() + 45
    new_pid = None
    while time.monotonic() < deadline:
        try:
            new_pid = h.remote(0).result(timeout=15)
            if new_pid != pid:
                break
        except Exception:
            time.sleep(0.3)
    assert new_pid is not None and new_pid != pid, \
        "controller never replaced the dead replica"


def test_autoscaling_up(serve_rt):
    @serve.deployment(name="scaly", num_replicas=1,
                      max_ongoing_requests=2,
                      autoscaling_config={
                          "min_replicas": 1, "max_replicas": 3,
                          "target_ongoing_requests": 1,
                          "upscale_delay_s": 0.2})
    class Slow:
        def __call__(self, _):
            time.sleep(1.0)
            return "ok"

    h = serve.run(Slow.bind())
    # sustain a burst so the controller sees queued work
    resps = [h.remote(i) for i in range(12)]
    deadline = time.monotonic() + 30
    grew = False
    while time.monotonic() < deadline:
        info = serve.status()["scaly"]
        if info["live_replicas"] >= 2:
            grew = True
            break
        time.sleep(0.25)
    for r in resps:
        r.result(timeout=120)
    assert grew, f"autoscaler never scaled up: {serve.status()}"


def test_crash_loop_marks_unhealthy(serve_rt):
    @serve.deployment(name="broken")
    class Broken:
        def __init__(self):
            raise RuntimeError("ctor-always-fails")

        def __call__(self, _):
            return 0

    serve.run(Broken.bind(), wait_for_replicas=False)
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        info = serve.status().get("broken", {})
        if info.get("unhealthy_reason"):
            break
        time.sleep(0.5)
    assert "consecutive replica failures" in \
        (serve.status()["broken"]["unhealthy_reason"] or ""), \
        "crash-looping deployment never marked unhealthy"
    serve.delete("broken")


def test_user_config_reconfigure_in_place(serve_rt):
    @serve.deployment(name="cfg", user_config={"factor": 2})
    class Cfg:
        def __init__(self):
            self.factor = 1

        def reconfigure(self, cfg):
            self.factor = cfg["factor"]

        def __call__(self, x):
            return (x * self.factor, os.getpid())

    h = serve.run(Cfg.bind())
    out, pid1 = h.remote(10).result(timeout=60)
    assert out == 20
    # redeploy with only user_config changed: same replica process, new cfg
    serve.run(Cfg.options(user_config={"factor": 5}).bind())
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        out, pid2 = h.remote(10).result(timeout=30)
        if out == 50:
            assert pid2 == pid1, "user_config change must not restart replicas"
            return
        time.sleep(0.2)
    pytest.fail("reconfigure never applied")


def test_serve_batch_coalesces_requests(serve_rt):
    @serve.deployment(name="batched", max_ongoing_requests=8)
    class Batched:
        def __init__(self):
            self.batch_sizes = []

        @serve.batch(max_batch_size=4, batch_wait_timeout_s=0.2)
        def infer(self, inputs):
            self.batch_sizes.append(len(inputs))
            return [x * 10 for x in inputs]

        def __call__(self, x):
            return self.infer(x)

        def sizes(self):
            return self.batch_sizes

    h = serve.run(Batched.bind())
    resps = [h.remote(i) for i in range(8)]
    out = sorted(r.result(timeout=60) for r in resps)
    assert out == [i * 10 for i in range(8)]
    sizes = h.sizes.remote().result(timeout=30)
    assert max(sizes) > 1, f"no coalescing happened: {sizes}"
    assert sum(sizes) == 8


def test_delete_deployment(serve_rt):
    @serve.deployment(name="gone")
    def f(_):
        return 1

    serve.run(f.bind())
    serve.delete("gone")
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline:
        if "gone" not in serve.status():
            return
        time.sleep(0.2)
    pytest.fail("deployment was not removed")


def test_deploy_from_spec_declarative(serve_rt, tmp_path):
    """Dict/YAML app specs deploy + reconcile declaratively (VERDICT #9;
    reference: serve/schema.py + build_app + `serve deploy`)."""
    import sys

    mod = tmp_path / "specmod_app.py"
    mod.write_text(
        "class Echo:\n"
        "    def __init__(self, prefix):\n"
        "        self.prefix = prefix\n"
        "    def __call__(self, x):\n"
        "        return f'{self.prefix}:{x}'\n"
        "\n"
        "def shout(x):\n"
        "    return str(x).upper()\n")
    sys.path.insert(0, str(tmp_path))
    import cloudpickle
    import importlib
    specmod = importlib.import_module("specmod_app")
    # replicas cannot import the tmp module by name: ship it by value
    # (the standard technique for code outside the cluster's sys.path)
    cloudpickle.register_pickle_by_value(specmod)
    try:
        spec = {
            "name": "app1",
            "deployments": [
                {"name": "echo", "import_path": "specmod_app:Echo",
                 "init_args": ["hi"], "num_replicas": 1},
                {"name": "shout", "import_path": "specmod_app:shout"},
            ],
        }
        status = serve.deploy_from_spec(spec)
        assert status["echo"]["ready_replicas"] >= 1
        assert serve.get_app_handle("echo").remote("x").result() == "hi:x"
        assert serve.get_app_handle("shout").remote("ab").result() == "AB"

        # YAML form + declarative diff: dropping 'shout' deletes it
        yaml_spec = (
            "name: app1\n"
            "deployments:\n"
            "  - name: echo\n"
            "    import_path: specmod_app:Echo\n"
            "    init_args: [hello]\n"
            "    num_replicas: 1\n")
        serve.deploy_from_spec(yaml_spec)
        assert serve.get_app_handle("echo").remote("y").result() == "hello:y"
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            if "shout" not in serve.status() \
                    or serve.status()["shout"]["deleted"]:
                break
            time.sleep(0.2)
        st = serve.status()
        assert "shout" not in st or st["shout"]["deleted"]

        with pytest.raises(ValueError, match="unknown deployment fields"):
            serve.deploy_from_spec({"deployments": [
                {"name": "x", "import_path": "specmod_app:Echo",
                 "bogus": 1}]})
    finally:
        sys.path.remove(str(tmp_path))
        serve.delete("echo")


def test_push_rerouting_on_replica_death(serve_rt):
    """Replica death reroutes via the pubsub PUSH (VERDICT #9): the
    router learns the new table in ~health-check time, far under the 30s
    lazy-staleness fallback window."""
    import ray_tpu

    @serve.deployment(num_replicas=2)
    def pong(x):
        return x + 1

    handle = serve.run(pong)
    assert handle.remote(1).result() == 2
    router = handle._router
    # force a fresh table so the router is demonstrably NOT stale now
    router._refresh(force=True)
    v0 = router._version
    assert len(router._replicas) == 2

    victim = router._replicas[0]
    ray_tpu.kill(victim)
    # the controller detects the death (health loop), bumps the version,
    # and PUSHES: the router's table must update well before the 30s
    # fallback could possibly fire
    t0 = time.monotonic()
    deadline = t0 + 15
    while time.monotonic() < deadline:
        if router._version != v0 and len(router._replicas) >= 1 \
                and all(h.actor_id != victim.actor_id
                        for h in router._replicas):
            break
        time.sleep(0.1)
    push_latency = time.monotonic() - t0
    assert router._version != v0, "router never saw the push"
    assert push_latency < Router_TABLE_MAX_AGE_GUARD, \
        f"table updated only after {push_latency:.1f}s (staleness window?)"
    # requests keep flowing on the survivor (and on the replacement)
    for i in range(5):
        assert handle.remote(i).result() == i + 1
    serve.delete("pong")


# the push must beat the fallback with wide margin; half the fallback
# window is a conservative ceiling even on a loaded CI host
from ray_tpu.serve.router import Router as _Router  # noqa: E402
Router_TABLE_MAX_AGE_GUARD = _Router.TABLE_MAX_AGE_S / 2
