"""Topic pub/sub: broker semantics, cross-process delivery, cluster
events (reference: src/ray/pubsub/ long-poll publisher/subscriber)."""

import threading
import time

import pytest

import ray_tpu
from ray_tpu.runtime.pubsub import PubsubBroker
from ray_tpu.util import pubsub


# ------------------------------------------------------- broker (unit)

def test_broker_roundtrip_and_cursors():
    b = PubsubBroker(epoch="e1")
    b.publish("t", {"n": 1})
    b.publish("t", {"n": 2})
    out = b.poll({"t": 0}, timeout_s=0)
    assert out["epoch"] == "e1"
    t = out["topics"]["t"]
    assert [m["n"] for m in t["messages"]] == [1, 2]
    cur = t["cursor"]
    assert b.poll({"t": cur}, timeout_s=0)["topics"] == {}
    b.publish("t", {"n": 3})
    out = b.poll({"t": cur}, timeout_s=0)
    assert [m["n"] for m in out["topics"]["t"]["messages"]] == [3]


def test_broker_longpoll_wakeup():
    b = PubsubBroker()
    got = {}

    def waiter():
        got["out"] = b.poll({"t": 0}, timeout_s=5)

    th = threading.Thread(target=waiter)
    th.start()
    time.sleep(0.1)
    t0 = time.monotonic()
    b.publish("t", "hello")
    th.join(timeout=5)
    assert not th.is_alive()
    # woke promptly, not at the poll deadline
    assert time.monotonic() - t0 < 1.0
    assert got["out"]["topics"]["t"]["messages"] == ["hello"]


def test_broker_ring_overflow_reports_drops():
    b = PubsubBroker(max_buffer=10)
    for i in range(25):
        b.publish("t", i)
    out = b.poll({"t": 0}, timeout_s=0)["topics"]
    assert out["t"]["messages"] == list(range(15, 25))
    assert out["t"]["dropped"] == 15


def test_broker_independent_topics():
    b = PubsubBroker()
    b.publish("a", 1)
    b.publish("b", 2)
    out = b.poll({"a": 0, "b": 0}, timeout_s=0)["topics"]
    assert out["a"]["messages"] == [1] and out["b"]["messages"] == [2]
    out = b.poll({"a": 1}, timeout_s=0)  # only a's cursor
    assert out["topics"] == {}


def test_subscriber_epoch_reset_resyncs():
    """A broker swap with a new epoch (the head-restart shape) rewinds
    subscriber cursors instead of silently stalling on stale ones."""
    from ray_tpu.util import pubsub as ps
    import ray_tpu
    ray_tpu.init(local_mode=True)
    try:
        with ps._local_lock:
            ps._local_broker = PubsubBroker(epoch="old")
        sub = ps.Subscriber("swap")
        ps.publish("swap", "before")
        assert sub.get(timeout=5) == ("swap", "before")
        # "head restart": fresh broker, fresh epoch, seqs restart at 0
        with ps._local_lock:
            ps._local_broker = PubsubBroker(epoch="new")
        ps.publish("swap", "after")
        # first pull notices the epoch change and rewinds; message lands
        assert sub.get(timeout=5) == ("swap", "after")
        assert sub._epoch == "new"
    finally:
        ray_tpu.shutdown()
        with ps._local_lock:
            ps._local_broker = None


# --------------------------------------------------- cluster (processes)

@pytest.fixture(scope="module")
def rt():
    ray_tpu.init(num_cpus=4)
    yield
    ray_tpu.shutdown()


@ray_tpu.remote
def _publisher_task(topic, n):
    for i in range(n):
        pubsub.publish(topic, {"i": i})
    return n


def test_pubsub_cross_process(rt):
    sub = pubsub.Subscriber("crossproc")
    assert ray_tpu.get(_publisher_task.remote("crossproc", 5)) == 5
    got = []
    while len(got) < 5:
        item = sub.get(timeout=10)
        assert item is not None, f"timed out after {len(got)} messages"
        got.append(item)
    assert [m["i"] for _, m in got] == list(range(5))


def test_pubsub_two_subscribers_independent(rt):
    s1 = pubsub.Subscriber("dup")
    s2 = pubsub.Subscriber("dup")
    pubsub.publish("dup", "x")
    assert s1.get(timeout=10) == ("dup", "x")
    assert s2.get(timeout=10) == ("dup", "x")


def test_cluster_events_on_actor_death(rt):
    @ray_tpu.remote(max_restarts=0)
    class Victim:
        def pid(self):
            import os
            return os.getpid()

    sub = pubsub.Subscriber("cluster_events")
    a = Victim.remote()
    ray_tpu.get(a.pid.remote())
    ray_tpu.kill(a)
    deadline = time.monotonic() + 20
    seen = []
    while time.monotonic() < deadline:
        item = sub.get(timeout=5)
        if item is None:
            continue
        seen.append(item[1])
        if any(e.get("event") == "actor_dead" for e in seen):
            break
    assert any(e.get("event") == "actor_dead" for e in seen), seen
