"""Driver benchmark: flagship-model training MFU on one TPU chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.
vs_baseline is measured MFU / the 45% north-star target (BASELINE.md §ML —
the reference publishes no in-tree ML numbers; 45% MFU is the driver-set
target).

Methodology: real training steps (bf16 compute, adafactor, remat,
donation) on a ~1.2B-param Llama. Steps dispatch pipelined through donated
buffers; only the FINAL loss is fetched, which bounds the whole timed
sequence (the device can't run ahead of its own data dependencies).
MFU convention: FLOPs/token = 6·N + 12·L·d·s, i.e. full (non-causal)
attention-score FLOPs — the PaLM-appendix convention — while the flash
kernels skip above-diagonal blocks, so the attention term credits ~2x the
score work actually done (<2% of total FLOPs at this size).

Round-3 sweep note: this shape is a verified local optimum on one v5e
(16 GB HBM). Denser alternatives all fail at compile for memory —
B=16/L=2048, B=8/L=4096, and remat_policy="dots" at B>=4 — and
"dots"@B=2 measures 47.1% vs full-remat@B=8's 48.1% (the recompute
saved is outweighed by the smaller batch's MXU utilization).
"""

from __future__ import annotations

import json
import sys
import time


# bf16 peak FLOP/s per chip by device kind (public spec sheets).
_PEAK = {
    "TPU v5 lite": 197e12,   # v5e
    "TPU v5": 459e12,        # v5p
    "TPU v4": 275e12,
    "TPU v6 lite": 918e12,   # v6e / Trillium
}


def _peak_flops(device) -> float:
    kind = getattr(device, "device_kind", "")
    for prefix, peak in sorted(_PEAK.items(), key=lambda kv: -len(kv[0])):
        if kind.startswith(prefix):
            return peak
    return 197e12


def _bench_8b_block(jax, llama, make_train_step, optax, dev) -> dict:
    """8B scaling evidence on one chip (round-4 verdict item 10): train
    ONE transformer block at Llama-3-8B dimensions (dim 4096, 32/8 heads,
    ffn 14336 — the exact per-layer compute of the v5p-64 north-star
    model, which exceeds single-chip HBM as a whole) and project:

      projected v5p-64 tokens/s = n_chips x peak_v5p x block_MFU
                                  / flops_per_token(8B)

    The projection's assumption — per-chip MFU carries from the measured
    block to the full model — is the standard one: 8B training is >99%
    per-layer block compute (32 identical blocks + embed/head), and fsdp
    gather/scatter overlaps compute on v5p's ICI.
    """
    cfg = llama.LlamaConfig(
        vocab_size=256,  # negligible embed/head: isolate the BLOCK
        dim=4096, n_layers=1, n_heads=32, n_kv_heads=8,
        ffn_dim=14336, attention="flash")
    # B=32 from the on-chip sweep (46.7% @ B=4/8 -> 48.9% @ B=32: one
    # block leaves HBM room the full model doesn't, so feed the MXU)
    B, L, steps, warmup = 32, 2048, 10, 2
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    init_fn, step_fn = make_train_step(
        lambda p, b: llama.loss_fn(p, b, cfg), optax.adafactor(1e-3))
    opt_state = init_fn(params)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (B, L), 0,
                                cfg.vocab_size)
    for _ in range(warmup):
        params, opt_state, m = step_fn(params, opt_state, tokens)
    float(m["loss"])
    t0 = time.perf_counter()
    for _ in range(steps):
        params, opt_state, m = step_fn(params, opt_state, tokens)
    float(m["loss"])
    dt = time.perf_counter() - t0

    tokens_per_sec = B * L * steps / dt
    flops_tok = llama.flops_per_token(cfg, L)
    block_mfu = tokens_per_sec * flops_tok / _peak_flops(dev)

    full = llama.LlamaConfig.llama3_8b()
    flops_tok_8b = llama.flops_per_token(full, 2048)
    v5p_peak, n_chips = _PEAK["TPU v5"], 64
    proj_tps = n_chips * v5p_peak * block_mfu / flops_tok_8b
    return {
        "llama8b_block_mfu": round(block_mfu * 100, 2),
        "llama8b_block_tokens_per_sec": round(tokens_per_sec, 1),
        "llama8b_block_params": llama.num_params(cfg),
        "v5p64_projection": {
            "model": "llama3-8b",
            "assumed_mfu": round(block_mfu * 100, 2),
            "projected_tokens_per_sec": round(proj_tps, 0),
            "arithmetic": (
                f"64 chips x {v5p_peak/1e12:.0f}e12 peak x "
                f"{block_mfu:.4f} MFU / {flops_tok_8b/1e9:.2f}e9 "
                f"FLOPs-per-token(8B@L2048)"),
            "note": ("per-layer block measured at true 8B dims on this "
                     "chip; BASELINE.md north star is >=45% MFU on "
                     "v5p-64 — the block MFU is the per-chip term of "
                     "that product"),
        },
    }


def _bench_checkpoint_overlap(jax) -> dict:
    """ISSUE 14 acceptance A/B: async checkpointing on vs off.

    One fixed compute step over a 32 MiB jax-array state; every 3rd step
    also checkpoints. Sync saves serialize+upload inline (step time pays
    the full write); async saves pay only the device->host copy on the
    training thread while the writer commits in the background. Budget:
    the worst step with an in-flight async save stays within 25% of the
    no-checkpoint baseline mean.
    """
    import os
    import shutil
    import tempfile

    import jax.numpy as jnp

    from ray_tpu.train.checkpoint import CheckpointManager

    tree = {f"w{i}": jnp.asarray(
        __import__("numpy").random.default_rng(i)
        .standard_normal((1024, 1024)).astype("float32"))
        for i in range(8)}  # 32 MiB of device state

    @jax.jit
    def compute(x):
        for _ in range(4):
            x = jnp.tanh(x @ x)
        return x

    x = compute(tree["w0"]).block_until_ready()
    steps, every = 18, 6
    # the step that CALLS save pays the device->host copy (sync mode also
    # pays serialize+upload+commit); the step AFTER an async submit runs
    # while the writer is mid-upload — THAT is the overlap claim
    submit_idx = [s - 1 for s in range(1, steps + 1) if s % every == 0]
    inflight_idx = [s - 1 for s in range(1, steps + 1)
                    if s % every == 1 and s > 1]

    def timed_run(save):
        nonlocal x
        ts = []
        for step in range(1, steps + 1):
            t0 = time.perf_counter()
            x = compute(x)
            x.block_until_ready()
            if save is not None and step % every == 0:
                save(step)
            ts.append(time.perf_counter() - t0)
        return ts

    base = timed_run(None)
    sync_root = tempfile.mkdtemp(prefix="bench_ckpt_sync_")
    async_root = tempfile.mkdtemp(prefix="bench_ckpt_async_")
    try:
        m_sync = CheckpointManager(sync_root, num_to_keep=2)
        sync = timed_run(lambda s: m_sync.save(tree, s))
        m_async = CheckpointManager(async_root, num_to_keep=2,
                                    async_save=True)
        asyn = timed_run(lambda s: m_async.save_async(tree, s))
        m_async.flush()
        shard_bytes = os.path.getsize(os.path.join(
            m_async.latest().path, "shard-00000.npz"))
    finally:
        shutil.rmtree(sync_root, ignore_errors=True)
        shutil.rmtree(async_root, ignore_errors=True)

    base_mean = sum(base) / len(base)
    sync_max = max(sync[i] for i in submit_idx)
    async_submit_max = max(asyn[i] for i in submit_idx)
    async_inflight_max = max(asyn[i] for i in inflight_idx)
    budget_pct = 25.0
    return {
        "baseline_step_ms": round(base_mean * 1e3, 2),
        "sync_save_step_max_ms": round(sync_max * 1e3, 2),
        "async_submit_step_max_ms": round(async_submit_max * 1e3, 2),
        "async_inflight_step_max_ms": round(async_inflight_max * 1e3, 2),
        "async_inflight_overhead_pct": round(
            (async_inflight_max - base_mean) / base_mean * 100, 1),
        "sync_overhead_pct": round(
            (sync_max - base_mean) / base_mean * 100, 1),
        "budget_pct": budget_pct,
        "within_budget": bool(
            async_inflight_max <= base_mean * (1 + budget_pct / 100)),
        "checkpoint_bytes": shard_bytes,
        "save_every_n_steps": every,
    }


def _bench_sharded_per_host_bytes() -> dict:
    """ISSUE 14 acceptance: per-host bytes written prove no host
    serialized the full tree. Two CPU worker processes save one
    FSDP-sharded model; the committed manifest records each host's shard
    size, so max_host_fraction << 1.0 is the no-gather proof."""
    import os
    import shutil
    import tempfile

    import ray_tpu as rt_
    from ray_tpu import train as rt_train
    from ray_tpu.parallel.mesh import MeshSpec
    from ray_tpu.train.checkpoint import MANIFEST_FILE

    def loop(cfg):
        import jax as _jax
        import optax as _optax

        from ray_tpu.models import llama as _llama
        from ray_tpu.train.train_step import make_train_step as _mts
        from ray_tpu.train.train_step import shard_params as _sp

        ctx = rt_train.get_context()
        mesh = ctx.global_mesh()
        mcfg = _llama.LlamaConfig.tiny(n_layers=2)
        params = _llama.init_params(mcfg, _jax.random.PRNGKey(11))
        with mesh:
            params = _sp(params, mesh, _llama.param_specs(mcfg))
            init_fn, _ = _mts(
                lambda p, b: _llama.loss_fn(p, b, mcfg), _optax.sgd(1e-2))
            init_fn(params)
            rt_train.report({"ok": 1}, checkpoint_tree={"params": params})

    storage = tempfile.mkdtemp(prefix="bench_ckpt_sharded_")
    rt_.init(num_cpus=4, _system_config={
        "object_store_memory_bytes": 128 * 1024 * 1024})
    try:
        result = rt_train.JaxTrainer(
            loop,
            scaling_config=rt_train.ScalingConfig(
                num_workers=2, mesh=MeshSpec(fsdp=-1),
                jax_distributed=True, jax_platform="cpu",
                local_device_count=4),
            run_config=rt_train.RunConfig(
                name="bench-sharded", storage_path=storage)).fit()
        if result.error is not None:
            raise result.error
        manifest = json.load(open(os.path.join(
            result.checkpoint.path, MANIFEST_FILE)))
        per_host = [s["bytes"] for s in manifest["shards"]]
        total = sum(per_host)
        return {
            "world_size": manifest["world_size"],
            "per_host_shard_bytes": per_host,
            "full_tree_bytes": total,
            "max_host_fraction": round(max(per_host) / total, 3),
        }
    finally:
        rt_.shutdown()
        shutil.rmtree(storage, ignore_errors=True)


def main() -> None:
    import dataclasses

    import jax
    import jax.numpy as jnp
    import optax

    from ray_tpu.models import llama
    from ray_tpu.train import make_train_step, profile_train_step

    dev = jax.devices()[0]
    on_tpu = (dev.platform == "tpu"
              or getattr(dev, "device_kind", "").startswith("TPU"))
    if on_tpu:
        # Chosen by on-chip sweep: wide layers (head_dim 128, 12k ffn) keep
        # the MXU fed; flash attention (Pallas fwd+bwd) never materializes
        # [L,L] scores; adafactor frees HBM for the 1.2B-param model.
        # remat_policy="selective" (save only matmul outputs) first — it
        # trims the backward recompute that full remat pays; if this shape
        # doesn't fit (r03 showed dots@B>=4 OOMs), fall back to "full".
        cfg = llama.LlamaConfig(
            vocab_size=32000, dim=3072, n_layers=8, n_heads=24,
            n_kv_heads=12, ffn_dim=12288, attention="flash",
            remat_policy="selective")
        B, L, steps, warmup = 8, 2048, 10, 2
    else:  # CI / no-TPU fallback keeps the contract observable
        cfg = llama.LlamaConfig.tiny(remat_policy="selective")
        B, L, steps, warmup = 4, 128, 4, 1

    tuned_blocks = None
    if cfg.attention == "flash":
        # eager sweep+cache so every later trace picks the tuned block
        from ray_tpu.ops import autotune_blocks
        tuned_blocks = autotune_blocks(L, L, cfg.head_dim, cfg.dtype)

    tokens = jax.random.randint(jax.random.PRNGKey(1), (B, L), 0,
                                cfg.vocab_size)

    def build_and_warm(cfg):
        params = llama.init_params(cfg, jax.random.PRNGKey(0))
        init_fn, step_fn = make_train_step(
            lambda p, b: llama.loss_fn(p, b, cfg), optax.adafactor(1e-3))
        opt_state = init_fn(params)
        t0 = time.perf_counter()
        params, opt_state, m = step_fn(params, opt_state, tokens)
        float(m["loss"])
        first_call_s = time.perf_counter() - t0  # compile + one step
        for _ in range(warmup - 1):
            params, opt_state, m = step_fn(params, opt_state, tokens)
        float(m["loss"])  # force sync after warmup
        return params, opt_state, step_fn, m, first_call_s

    try:
        params, opt_state, step_fn, m, first_call_s = build_and_warm(cfg)
    except Exception:  # noqa: BLE001 — selective remat didn't fit/compile
        if cfg.remat_policy == "full":
            raise
        cfg = dataclasses.replace(cfg, remat_policy="full")
        params, opt_state, step_fn, m, first_call_s = build_and_warm(cfg)

    # Steps chain through donated buffers, so the final fetch bounds the
    # whole sequence — standard pipelined-dispatch timing.
    t0 = time.perf_counter()
    for _ in range(steps):
        params, opt_state, m = step_fn(params, opt_state, tokens)
    final_loss = float(m["loss"])
    dt = time.perf_counter() - t0
    assert final_loss == final_loss, "NaN loss"

    tokens_per_sec = B * L * steps / dt
    flops_tok = llama.flops_per_token(cfg, L)
    mfu = tokens_per_sec * flops_tok / _peak_flops(dev)
    # compile time = first call minus one steady-state step, reported
    # SEPARATELY so warm-up can never leak into the steady-state MFU
    compile_time_s = max(first_call_s - dt / steps, 0.0)

    # per-phase attribution of the same step (fresh non-donating programs;
    # additive evidence — the headline number above is already banked)
    try:
        bd = profile_train_step(
            lambda p, b: llama.loss_fn(p, b, cfg), optax.adafactor(1e-3),
            params, opt_state, tokens, steps=3, warmup=1, emit=False)
        phase_breakdown = {k: round(v, 2) for k, v in bd.phase_ms().items()}
    except Exception as e:  # noqa: BLE001
        phase_breakdown = {"error": repr(e)[:160]}

    # async-checkpoint A/B + sharded-save proof (ISSUE 14); additive —
    # failures here must not cost the headline MFU line
    try:
        ckpt_overlap = _bench_checkpoint_overlap(jax)
    except Exception as e:  # noqa: BLE001
        ckpt_overlap = {"error": repr(e)[:200]}
    # child process: the embedded cluster logs READY lines to stdout,
    # which must not pollute this process's single-JSON-line contract
    try:
        import subprocess
        import tempfile

        out = tempfile.mktemp(suffix=".json")
        subprocess.run([sys.executable, __file__,
                        "--sharded-ckpt-proof", out],
                       capture_output=True, timeout=300, check=True)
        ckpt_overlap["sharded"] = json.load(open(out))
    except Exception as e:  # noqa: BLE001
        ckpt_overlap["sharded"] = {"error": repr(e)[:200]}
    try:
        with open("BENCH_ckpt.json", "w") as f:
            json.dump({"metric": "checkpoint_overlap_ab",
                       **ckpt_overlap}, f, indent=1)
    except OSError:
        pass

    extra = {}
    if on_tpu:
        # free the 1.2B model's buffers first: the B=32 block bench needs
        # the HBM the headline model occupies
        del params, opt_state, tokens, step_fn, m
        import gc
        gc.collect()
        try:
            extra = _bench_8b_block(jax, llama, make_train_step, optax, dev)
        except Exception as e:  # noqa: BLE001 — 8B-block evidence is
            extra = {"llama8b_block_error": repr(e)[:200]}  # additive
    print(json.dumps({
        "metric": "llama_train_mfu_1chip",
        "value": round(mfu * 100, 2),
        "unit": "percent_of_peak_bf16",
        "vs_baseline": round(mfu * 100 / 45.0, 4),
        "target_mfu_pct": 52.0,  # BENCH_r07 goal (ROADMAP item 3)
        "tokens_per_sec": round(tokens_per_sec, 1),
        "step_time_ms": round(dt / steps * 1e3, 1),
        "compile_time_s": round(compile_time_s, 2),
        "phase_breakdown_ms": phase_breakdown,
        "remat_policy": cfg.remat_policy,
        "flash_blocks": list(tuned_blocks) if tuned_blocks else None,
        "n_params": llama.num_params(cfg),
        "device": str(getattr(dev, "device_kind", dev.platform)),
        "batch": B, "seq_len": L, "optimizer": "adafactor",
        "final_loss": round(final_loss, 3),
        "checkpoint_overlap": ckpt_overlap,
        **extra,
    }))


if __name__ == "__main__":
    if len(sys.argv) == 3 and sys.argv[1] == "--sharded-ckpt-proof":
        with open(sys.argv[2], "w") as f:
            json.dump(_bench_sharded_per_host_bytes(), f)
        sys.exit(0)
    try:
        main()
    except Exception as e:  # noqa: BLE001 — the driver needs a line either way
        print(json.dumps({"metric": "llama_train_mfu_1chip", "value": 0.0,
                          "unit": "percent_of_peak_bf16", "vs_baseline": 0.0,
                          "error": repr(e)[:300]}))
        sys.exit(1)
